"""Fleet observability plane (ISSUE 17): endpoint discovery, the
scrape client + FleetMonitor, and fleet-level aggregation.

Three contracts, each mirroring a discipline the repo already proved
single-process:

- **Endpoint discovery** -- every obs server publishes
  ``{pid, rank, generation, port, started_at}`` to
  ``MXNET_TPU_OBS_ENDPOINTS_DIR`` as ``r<rank>.<pid>.json`` through the
  checkpoint-core atomic commit (torn registrations cannot exist), and
  every publish sweeps sibling files whose writer pid is dead -- the
  PR-3 stale-tmp discipline applied to registrations.  The PR-15
  supervisor threads the directory into every launched world, so a
  relaunched generation re-registers under the same rank with a new
  pid/generation automatically.
- **Scrape + typed snapshots** -- :func:`scrape` polls one replica's
  ``/healthz`` + ``/statusz`` + ``/metrics`` into a
  :class:`ReplicaSnapshot`; ``/statusz`` replies carrying an unknown
  ``schema`` are rejected LOUDLY (:class:`SchemaMismatch`) -- the
  cross-process contract a silent parse-anyway would rot.  The
  :class:`FleetMonitor` polls every discovered endpoint with
  per-replica timeout/retry/backoff; a replica that stops answering is
  *sick*, and stale-past-TTL (or a provably dead pid) flips it to
  *presumed down* -- the PR-15 lease discipline.
- **Aggregation** -- per round the monitor pools each replica's DELTAS
  (never lifetime totals) into fleet QPS, summed queue depth, shed and
  error ratios, and latency percentiles computed by MERGING the Timer
  histogram buckets across replicas (:class:`MergedHistogram`; the
  fixed power-of-2 bucket grid makes cross-process merge exact) --
  averaging per-replica p99s is statistically meaningless and a test
  proves it wrong.  Served-step and goodput-category skew generalize
  the PR-14 straggler attribution across processes.

The :class:`~mxnet_tpu.obs.alerts.AlertEngine` rides every round;
``/alertz`` (obs.server) and ``mxtelemetry fleet`` render the result.
"""
from __future__ import annotations

import http.client
import json
import os
import re
import socket
import time
import urllib.error
import urllib.request

from .. import sync as _sync
from ..base import MXNetError
from . import alerts as _alerts

__all__ = [
    "Endpoint", "ReplicaSnapshot", "FleetMonitor", "MergedHistogram",
    "ScrapeError", "SchemaMismatch", "STATUSZ_SCHEMA",
    "publish_endpoint", "remove_endpoint", "sweep_endpoints",
    "discover", "scrape", "active",
]

# The /statusz contract version this scrape client speaks.  Bump it
# when the statusz shape changes incompatibly; the client REFUSES
# unknown schemas instead of guessing.
STATUSZ_SCHEMA = "mxstatusz.v1"

_ENDPOINT_RE = re.compile(r"^r(\d+)\.(\d+)\.json$")

# Published endpoint paths owned by THIS process (removed on
# server.stop()); monitors running in this process (Features FLEET).
_published = []
_monitors = []


class ScrapeError(MXNetError):
    """A replica scrape failed (refused/timed out/garbage payload)."""


class SchemaMismatch(ScrapeError):
    """A replica answered /statusz with a schema this client does not
    speak -- a version-skewed or foreign process; never parse it."""


# ----------------------------------------------------------------------
# endpoint discovery contract
# ----------------------------------------------------------------------

def _endpoints_dir(dirpath=None):
    if dirpath is None:
        dirpath = os.environ.get("MXNET_TPU_OBS_ENDPOINTS_DIR", "")
    return dirpath or None


def _rank():
    try:
        return int(os.environ.get("MXNET_TPU_PROC_ID", "0") or 0)
    except ValueError:
        return 0


def _generation():
    try:
        return int(os.environ.get("MXNET_TPU_GENERATION", "0") or 0)
    except ValueError:
        return 0


class Endpoint:
    """One discovered obs-server registration."""

    __slots__ = ("pid", "rank", "generation", "port", "started_at",
                 "path")

    def __init__(self, pid, rank, generation, port, started_at,
                 path=None):
        self.pid = int(pid)
        self.rank = int(rank)
        self.generation = int(generation)
        self.port = int(port)
        self.started_at = float(started_at)
        self.path = path

    @property
    def url(self):
        return "http://127.0.0.1:%d" % self.port

    def as_dict(self):
        return {"pid": self.pid, "rank": self.rank,
                "generation": self.generation, "port": self.port,
                "started_at": self.started_at}

    def __repr__(self):
        return ("Endpoint(rank=%d gen=%d pid=%d port=%d)"
                % (self.rank, self.generation, self.pid, self.port))


def sweep_endpoints(dirpath):
    """Remove endpoint files whose writer pid is dead -- the PR-3
    stale-tmp sweep applied to registrations.  Live pids (including
    ours) are left alone.  Returns the removed paths."""
    from ..checkpoint.core import _pid_alive
    removed = []
    try:
        entries = os.listdir(dirpath)
    except OSError:
        return removed
    for name in entries:
        m = _ENDPOINT_RE.match(name)
        if m is None:
            continue
        pid = int(m.group(2))
        if pid == os.getpid() or _pid_alive(pid):
            continue
        path = os.path.join(dirpath, name)
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed


def publish_endpoint(port, dirpath=None, rank=None, generation=None):
    """Atomically publish this process's obs endpoint to the discovery
    directory (``MXNET_TPU_OBS_ENDPOINTS_DIR`` when ``dirpath`` is
    None; unset = no-op returning None).  Uses the checkpoint-core
    atomic commit, so a reader can never observe a torn registration,
    and sweeps dead-pid siblings first so a crashed generation's
    residue never outlives its relaunch."""
    from ..checkpoint.core import atomic_write_bytes
    dirpath = _endpoints_dir(dirpath)
    if dirpath is None:
        return None
    rank = _rank() if rank is None else int(rank)
    generation = _generation() if generation is None else int(generation)
    os.makedirs(dirpath, exist_ok=True)
    sweep_endpoints(dirpath)
    ep = Endpoint(os.getpid(), rank, generation, port, time.time())
    path = os.path.join(dirpath, "r%d.%d.json" % (rank, os.getpid()))
    atomic_write_bytes(path, json.dumps(ep.as_dict(),
                                        sort_keys=True).encode())
    ep.path = path
    _published.append(path)
    return path


def remove_endpoint(path=None):
    """Withdraw this process's registration(s) -- the clean-departure
    path (obs.server.stop()); a dead-pid sweep covers the crash path."""
    paths = [path] if path is not None else list(_published)
    for p in paths:
        try:
            os.remove(p)
        except OSError:
            pass
        if p in _published:
            _published.remove(p)


def discover(dirpath):
    """Parse every endpoint file in ``dirpath`` into Endpoints, newest
    generation winning per rank.  Unparseable files are skipped (the
    atomic publish makes torn files impossible; garbage means a foreign
    writer, and discovery must not die on it)."""
    by_rank = {}
    try:
        entries = os.listdir(dirpath)
    except OSError:
        return []
    for name in sorted(entries):
        if _ENDPOINT_RE.match(name) is None:
            continue
        path = os.path.join(dirpath, name)
        try:
            with open(path) as f:
                d = json.load(f)
            ep = Endpoint(d["pid"], d["rank"], d["generation"],
                          d["port"], d.get("started_at", 0.0),
                          path=path)
        except (OSError, ValueError, KeyError, TypeError):
            continue
        old = by_rank.get(ep.rank)
        if old is None or (ep.generation, ep.started_at) \
                >= (old.generation, old.started_at):
            by_rank[ep.rank] = ep
    return [by_rank[r] for r in sorted(by_rank)]


def active():
    """Whether this process participates in the fleet plane (publishes
    an endpoint or runs a monitor) -- the Features() FLEET row."""
    return bool(_endpoints_dir() or _published or _monitors)


# ----------------------------------------------------------------------
# scrape client
# ----------------------------------------------------------------------

def _http_json(url, timeout_s):
    """GET ``url`` -> parsed JSON; 503 bodies parse too (NOT_READY is
    an answer, not a failure)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            body = r.read()
    except urllib.error.HTTPError as e:
        if e.code != 503:
            raise ScrapeError("GET %s -> HTTP %d" % (url, e.code)) from e
        body = e.read()
    except (urllib.error.URLError, socket.timeout, OSError,
            http.client.HTTPException) as e:
        # URLError: refused/unreachable; timeout: a hung replica;
        # HTTPException incl. IncompleteRead: a replica that died
        # mid-response -- every one is "this scrape failed", typed
        raise ScrapeError("GET %s failed: %s" % (url, e)) from e
    try:
        return json.loads(body)
    except ValueError as e:
        raise ScrapeError("GET %s returned unparseable JSON (%d bytes)"
                          % (url, len(body))) from e


def _http_text(url, timeout_s):
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return r.read().decode("utf-8", "replace")
    except (urllib.error.URLError, socket.timeout, OSError,
            http.client.HTTPException) as e:
        raise ScrapeError("GET %s failed: %s" % (url, e)) from e


_PROM_LINE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$')
_PROM_LE = re.compile(r'le="([^"]+)"')


def _parse_prom(text):
    """Prometheus text exposition -> ``(values, buckets)``:
    ``values[name]`` = plain sample (counters/gauges/_count/_sum),
    ``buckets[base]`` = cumulative ``{le_seconds: count}`` per
    histogram (``+Inf`` folded in as ``inf``)."""
    values, buckets = {}, {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            continue
        name, labels, raw = m.group("name", "labels", "value")
        try:
            value = float(raw)
        except ValueError:
            continue
        if labels and name.endswith("_bucket"):
            le = _PROM_LE.search(labels)
            if le is None:
                continue
            bound = float("inf") if le.group(1) == "+Inf" \
                else float(le.group(1))
            buckets.setdefault(name[:-len("_bucket")], {})[bound] = value
        elif not labels:
            values[name] = value
    return values, buckets


class ReplicaSnapshot:
    """One successful scrape of one replica, typed."""

    __slots__ = ("url", "t", "ready", "reasons", "rank", "generation",
                 "pid", "served_step", "published_step", "queue_depth",
                 "counters", "latency", "goodput", "statusz")

    def __init__(self, url, t, ready, reasons, statusz, counters,
                 latency):
        self.url = url
        self.t = t
        self.ready = bool(ready)
        self.reasons = list(reasons or ())
        self.statusz = statusz
        self.rank = statusz.get("rank")
        self.generation = statusz.get("generation")
        self.pid = statusz.get("pid")
        self.served_step = statusz.get("served_step")
        self.published_step = statusz.get("published_step")
        self.queue_depth = sum(s.get("queue_depth") or 0
                               for s in statusz.get("servables", ()))
        self.counters = counters        # requests/responses/shed/...
        self.latency = latency          # cumulative {le_s: count}
        self.goodput = statusz.get("goodput")

    def __repr__(self):
        return ("ReplicaSnapshot(rank=%s gen=%s ready=%s reqs=%s)"
                % (self.rank, self.generation, self.ready,
                   self.counters.get("requests")))


def _prom(name):
    from ..telemetry.sinks import _prom_name
    return _prom_name(name)


# the serving counters a fleet aggregate is built from, by their
# dotted instrument names (mangled to prom names at parse time)
_SCRAPED_COUNTERS = {
    "requests": "serving.requests",
    "responses": "serving.responses",
    "shed": "serving.shed",
    "timeouts": "serving.timeouts",
    "errors": "serving.errors",
}


def scrape(url, timeout_s=1.0):
    """Poll one replica's three endpoints into a ReplicaSnapshot.
    Raises :class:`ScrapeError` on any transport/parse failure and
    :class:`SchemaMismatch` on an unknown /statusz schema."""
    url = url.rstrip("/")
    health = _http_json(url + "/healthz", timeout_s)
    statusz = _http_json(url + "/statusz", timeout_s)
    if not isinstance(statusz, dict):
        raise ScrapeError("%s/statusz is not a JSON object" % url)
    schema = statusz.get("schema")
    if schema != STATUSZ_SCHEMA:
        raise SchemaMismatch(
            "%s/statusz speaks schema %r, this client speaks %r -- "
            "refusing to parse a version-skewed replica"
            % (url, schema, STATUSZ_SCHEMA))
    values, buckets = _parse_prom(_http_text(url + "/metrics",
                                             timeout_s))
    counters = {key: values.get(_prom(name), 0.0)
                for key, name in _SCRAPED_COUNTERS.items()}
    latency = dict(buckets.get(_prom("serving.latency"), {}))
    return ReplicaSnapshot(
        url, time.time(),
        ready=health.get("status") == "READY",
        reasons=health.get("reasons"),
        statusz=statusz, counters=counters, latency=latency)


# ----------------------------------------------------------------------
# histogram merge -- NEVER average percentiles
# ----------------------------------------------------------------------

def _per_bucket(cum):
    """Cumulative ``{le: count}`` -> per-bucket increments (the +Inf
    entry absorbs anything past the last finite bound)."""
    out = {}
    prev = 0.0
    for le in sorted(cum):
        n = cum[le] - prev
        prev = cum[le]
        if n > 0:
            out[le] = out.get(le, 0.0) + n
    return out


class MergedHistogram:
    """Bucket-wise sum of Timer histograms across replicas/rounds.

    Because every Timer shares the fixed power-of-2 bucket grid
    (telemetry.core._TIMER_BUCKETS), cross-process merge is an exact
    per-bucket addition, and a percentile of the merged histogram is
    the same estimator a single pooled Timer would have produced --
    correct within one bucket (a factor of 2).  The mean of
    per-replica p99s has NO such guarantee: a quiet replica's p99
    counts as much as a busy one's, and tests/test_fleet.py pins a
    case where the average is off by an order of magnitude."""

    __slots__ = ("_buckets",)

    def __init__(self):
        self._buckets = {}      # le upper bound (s) -> count in bucket

    def add_buckets(self, per_bucket):
        """Fold per-bucket (non-cumulative) ``{le: n}`` counts in."""
        for le, n in per_bucket.items():
            if n:
                self._buckets[float(le)] = \
                    self._buckets.get(float(le), 0.0) + n

    def add_cumulative(self, cum):
        """Fold a prom-style cumulative ``{le: count}`` histogram in."""
        self.add_buckets(_per_bucket(cum))

    def merge(self, other):
        self.add_buckets(other._buckets)
        return self

    @property
    def count(self):
        return sum(self._buckets.values())

    def percentile(self, q):
        """Histogram-estimated q-quantile: the upper bound of the
        bucket where the cumulative count crosses ``q * count`` (the
        Timer.percentile algorithm over the merged buckets)."""
        total = self.count
        if not total:
            return None
        rank = q * total
        acc = 0.0
        est = None
        for le in sorted(self._buckets):
            acc += self._buckets[le]
            est = le
            if acc >= rank:
                break
        return est

    def snapshot(self):
        return dict(self._buckets)


def _delta_hist(cur, prev):
    """Per-bucket delta between two cumulative histograms (a fresh
    replica's first scrape has no previous -> empty delta; lifetime
    history must not pollute a live SLO window)."""
    a, b = _per_bucket(cur), _per_bucket(prev)
    out = {}
    for le, n in a.items():
        d = n - b.get(le, 0.0)
        if d > 0:
            out[le] = d
    return out


# ----------------------------------------------------------------------
# the monitor
# ----------------------------------------------------------------------

class _Replica:
    """Per-endpoint scrape state (keyed by rank in directory mode, by
    URL in explicit-URL mode)."""

    __slots__ = ("key", "url", "endpoint", "snapshot", "prev",
                 "last_ok_t", "failures", "last_error", "down_since",
                 "file_gone")

    def __init__(self, key, url, endpoint=None):
        self.key = key
        self.url = url
        self.endpoint = endpoint
        self.snapshot = None        # last good ReplicaSnapshot
        self.prev = None            # the one before (delta basis)
        self.last_ok_t = None
        self.failures = 0
        self.last_error = None
        self.down_since = None
        self.file_gone = False

    @property
    def rank(self):
        if self.snapshot is not None and self.snapshot.rank is not None:
            return self.snapshot.rank
        return self.endpoint.rank if self.endpoint is not None else None

    @property
    def generation(self):
        if self.snapshot is not None \
                and self.snapshot.generation is not None:
            return self.snapshot.generation
        return self.endpoint.generation if self.endpoint is not None \
            else None

    @property
    def pid(self):
        if self.endpoint is not None:
            return self.endpoint.pid
        return self.snapshot.pid if self.snapshot is not None else None

    def state(self, now, ttl_s):
        if self.down_since is not None:
            return "down"
        if self.failures == 0 and self.snapshot is None:
            return "init"
        if self.failures == 0:
            return "ok"
        if self.last_ok_t is not None and now - self.last_ok_t <= ttl_s:
            return "sick"
        if self.last_ok_t is None and self.snapshot is None \
                and not self._pid_dead() and not self.file_gone:
            # never answered yet and not provably dead: still starting
            return "sick"
        return "down"

    def _pid_dead(self):
        from ..checkpoint.core import _pid_alive
        pid = self.pid
        return pid is not None and not _pid_alive(pid)


class FleetMonitor:
    """Background poller over the discovered fleet.

    ``source`` is either the endpoints directory (discovery mode: the
    replica set follows the directory, keyed by rank so a relaunched
    generation REPLACES its predecessor) or an explicit list of base
    URLs.  ``poll_once()`` runs one scrape round synchronously and
    returns the fleet snapshot; ``start()`` runs rounds on a daemon
    thread every ``scrape_ms``.

    The monitor must never crash or wedge on a sick replica: every
    scrape is bounded by ``timeout_s``, retried ``retries`` times with
    doubling backoff from ``backoff_s``, and any failure only updates
    that replica's state.  A replica is *presumed down* when its data
    is stale past ``ttl_s`` (default 3 scrape intervals), when its
    registered pid is provably dead, or when its endpoint file vanished
    while it was failing -- the PR-15 lease discipline.
    """

    def __init__(self, source, scrape_ms=None, ttl_s=None,
                 timeout_s=None, retries=1, backoff_s=0.05,
                 rules=None, window_s=None):
        if scrape_ms is None:
            from .. import env as _env
            scrape_ms = _env.get("MXNET_TPU_OBS_SCRAPE_MS")
        self.scrape_s = max(float(scrape_ms) / 1e3, 1e-3)
        self.ttl_s = float(ttl_s) if ttl_s is not None \
            else 3.0 * self.scrape_s
        self.timeout_s = float(timeout_s) if timeout_s is not None \
            else max(min(1.0, self.scrape_s), 0.05)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.window_s = float(window_s) if window_s is not None \
            else max(60.0, 3.0 * self.scrape_s)
        if isinstance(source, str):
            self.endpoints_dir = source
            self.urls = None
        else:
            self.endpoints_dir = None
            self.urls = [u.rstrip("/") for u in source]
        self.engine = _alerts.AlertEngine(rules=rules)
        self._replicas = {}
        self._window = []           # (t, delta record) rolling ring
        self._lock = _sync.Lock(name="obs.fleet_monitor")
        self._stop = _sync.Event(name="obs.fleet_monitor_stop")
        self._thread = None
        self.last = None            # newest fleet snapshot dict
        self.rounds = 0
        _monitors.append(self)
        from . import status as _status
        _status.register_fleet(self)

    # -- discovery -----------------------------------------------------
    def _refresh_targets(self):
        if self.urls is not None:
            for url in self.urls:
                if url not in self._replicas:
                    self._replicas[url] = _Replica(url, url)
            return
        seen = set()
        for ep in discover(self.endpoints_dir):
            seen.add(ep.rank)
            rep = self._replicas.get(ep.rank)
            if rep is None or rep.endpoint is None \
                    or rep.endpoint.pid != ep.pid \
                    or rep.endpoint.generation != ep.generation:
                # new rank, or a relaunch: fresh state (the old
                # generation's lifetime counters must not delta
                # against the new one's)
                self._replicas[ep.rank] = _Replica(ep.rank, ep.url, ep)
            else:
                rep.endpoint = ep
                rep.file_gone = False
        for rank, rep in list(self._replicas.items()):
            if rank in seen:
                continue
            if rep.snapshot is not None and rep.failures == 0:
                # healthy and cleanly deregistered: departed, drop
                del self._replicas[rank]
            else:
                rep.file_gone = True

    # -- one scrape round ----------------------------------------------
    def _scrape_one(self, rep, now):
        last_err = None
        for attempt in range(self.retries + 1):
            if attempt:
                time.sleep(min(self.backoff_s * (2 ** (attempt - 1)),
                               self.scrape_s))
            try:
                snap = scrape(rep.url, timeout_s=self.timeout_s)
            except ScrapeError as e:
                last_err = e
                continue
            except Exception as e:      # a sick replica must never
                last_err = e            # crash the monitor
                continue
            rep.prev, rep.snapshot = rep.snapshot, snap
            rep.last_ok_t = snap.t
            rep.failures = 0
            rep.last_error = None
            rep.down_since = None
            self._hook(lambda h: h.fleet_scrape(True))
            return True
        rep.failures += 1
        rep.last_error = str(last_err)
        self._hook(lambda h: h.fleet_scrape(False))
        return False

    @staticmethod
    def _hook(fn):
        from .. import telemetry as _telemetry
        if _telemetry._ENABLED:
            fn(_telemetry.hooks)

    def poll_once(self, now=None):
        """One synchronous round: refresh targets, scrape every
        replica, aggregate, evaluate alerts.  Returns (and stores as
        ``self.last``) the fleet snapshot dict."""
        with self._lock:
            return self._poll_locked(now)

    def _poll_locked(self, now):
        self._refresh_targets()
        for rep in self._replicas.values():
            self._scrape_one(rep, now)
        now = time.time() if now is None else float(now)
        # lease discipline: stale-past-TTL / dead pid => presumed down
        down = []
        rows = []
        for key in sorted(self._replicas,
                          key=lambda k: (str(type(k)), k)):
            rep = self._replicas[key]
            if rep.failures and rep._pid_dead():
                rep.last_ok_t = None        # dead is dead: no TTL grace
            state = rep.state(now, self.ttl_s)
            if state == "down":
                if rep.down_since is None:
                    rep.down_since = now
                    self._hook(lambda h, r=rep: h.fleet_replica_down(
                        r.rank, r.generation, r.last_error))
                down.append(rep)
            rows.append(self._row(rep, state))
        agg = self._aggregate(now, down)
        changed = self.engine.observe(
            {"p99_latency_ms": agg["latency_ms"]["p99"],
             "shed_ratio": agg["shed_ratio"],
             "error_ratio": agg["error_ratio"],
             "replica_down": float(len(down))},
            detail={"replica_down": "; ".join(
                "rank %s generation %s (pid %s) %s"
                % (r.rank, r.generation, r.pid,
                   r.last_error or "stale past TTL") for r in down)},
            now=now)
        snap = {
            "t": now,
            "replicas": rows,
            "aggregate": agg,
            "alerts": {
                "firing": [a.as_dict() for a in self.engine.firing()],
                "pending": [a.as_dict() for a in self.engine.active()
                            if a.state == "pending"],
                "transitions": [a.as_dict() for a in changed],
            },
        }
        self.last = snap
        self.rounds += 1
        self._publish(agg, down)
        return snap

    def _row(self, rep, state):
        s = rep.snapshot
        row = {"key": rep.key, "url": rep.url, "state": state,
               "rank": rep.rank, "generation": rep.generation,
               "pid": rep.pid, "failures": rep.failures,
               "last_error": rep.last_error}
        if s is not None:
            hist = MergedHistogram()
            hist.add_cumulative(s.latency)
            row.update({
                "ready": s.ready, "reasons": s.reasons,
                "served_step": s.served_step,
                "published_step": s.published_step,
                "queue_depth": s.queue_depth,
                "requests": s.counters.get("requests"),
                "shed": s.counters.get("shed"),
                "errors": (s.counters.get("errors", 0)
                           + s.counters.get("timeouts", 0)),
                "latency_p99_ms": _ms(hist.percentile(0.99)),
            })
        return row

    # -- aggregation ---------------------------------------------------
    def _round_deltas(self, now):
        """Pool each replica's counter/histogram deltas since its
        previous good scrape into one per-round record."""
        rec = {"t": now, "hist": MergedHistogram(), "requests": 0.0,
               "responses": 0.0, "shed": 0.0, "errors": 0.0,
               "span_s": 0.0}
        for rep in self._replicas.values():
            cur, prev = rep.snapshot, rep.prev
            if cur is None or prev is None or cur is prev:
                continue
            if cur.t <= prev.t:
                continue
            rec["hist"].add_buckets(_delta_hist(cur.latency,
                                                prev.latency))
            for k in ("requests", "responses", "shed"):
                rec[k] += max(cur.counters.get(k, 0.0)
                              - prev.counters.get(k, 0.0), 0.0)
            rec["errors"] += max(
                (cur.counters.get("errors", 0.0)
                 + cur.counters.get("timeouts", 0.0))
                - (prev.counters.get("errors", 0.0)
                   + prev.counters.get("timeouts", 0.0)), 0.0)
            rec["span_s"] = max(rec["span_s"], cur.t - prev.t)
        return rec

    def _aggregate(self, now, down):
        rec = self._round_deltas(now)
        self._window.append(rec)
        horizon = now - self.window_s
        self._window = [r for r in self._window if r["t"] >= horizon]
        hist = MergedHistogram()
        reqs = resp = shed = errs = span = 0.0
        for r in self._window:
            hist.merge(r["hist"])
            reqs += r["requests"]
            resp += r["responses"]
            shed += r["shed"]
            errs += r["errors"]
            span += r["span_s"]
        ups = [rep for rep in self._replicas.values()
               if rep.snapshot is not None and rep.down_since is None]
        served = [rep.snapshot.served_step for rep in ups
                  if rep.snapshot.served_step is not None]
        accepted = reqs + shed
        return {
            "replicas": len(self._replicas),
            "up": len(ups),
            "down": len(down),
            "qps": (reqs / span) if span > 0 else None,
            "queue_depth": sum(rep.snapshot.queue_depth
                               for rep in ups),
            "shed_ratio": (shed / accepted) if accepted else None,
            "error_ratio": (errs / (resp + errs))
            if (resp + errs) else None,
            "latency_ms": {
                "p50": _ms(hist.percentile(0.50)),
                "p95": _ms(hist.percentile(0.95)),
                "p99": _ms(hist.percentile(0.99)),
                "samples": hist.count,
            },
            "served_step": {
                "min": min(served) if served else None,
                "max": max(served) if served else None,
                "skew": (max(served) - min(served)) if served else None,
            },
            "goodput_skew": _goodput_skew(ups),
        }

    def _publish(self, agg, down):
        def emit(h):
            h.fleet_round(agg)
            h.fleet_alerts_firing(len(self.engine.firing()))
        self._hook(emit)

    # -- background loop ----------------------------------------------
    def start(self):
        """Run rounds on a daemon thread every ``scrape_ms``
        (idempotent)."""
        import threading
        if self._thread is not None:
            return self
        self._stop.clear()
        t = threading.Thread(target=self._loop, daemon=True,
                             name="mxtpu-fleet-monitor")
        t.start()
        self._thread = t
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:       # the monitor never dies; a broken
                pass                # round just skips to the next one
            self._stop.wait(self.scrape_s)

    def close(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)
        if self in _monitors:
            _monitors.remove(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- rendering -----------------------------------------------------
    def fleet_row(self):
        """The compact /statusz row (obs.status)."""
        agg = (self.last or {}).get("aggregate") or {}
        return {"replicas": agg.get("replicas", 0),
                "up": agg.get("up", 0),
                "down": agg.get("down", 0),
                "alerts_firing": len(self.engine.firing())}

    def table(self):
        """The human fleet table + alert lines the CLI renders."""
        snap = self.last or {}
        lines = ["fleet: %d replica(s), %d up / %d down"
                 % tuple((snap.get("aggregate") or {}).get(k, 0)
                         for k in ("replicas", "up", "down"))]
        lines.append("  %-5s %-4s %-7s %-6s %-7s %-9s %-9s %s"
                     % ("rank", "gen", "state", "ready", "queue",
                        "requests", "p99", "url"))
        lines.append("  " + "-" * 70)
        for r in snap.get("replicas", ()):
            p99 = r.get("latency_p99_ms")
            lines.append(
                "  %-5s %-4s %-7s %-6s %-7s %-9s %-9s %s"
                % (r.get("rank", "?"), r.get("generation", "?"),
                   r["state"],
                   {True: "yes", False: "NO"}.get(r.get("ready"), "-"),
                   r.get("queue_depth", "-"),
                   ("%d" % r["requests"])
                   if r.get("requests") is not None else "-",
                   ("%.1fms" % p99) if p99 is not None else "-",
                   r["url"]))
        agg = snap.get("aggregate") or {}
        lat = agg.get("latency_ms") or {}
        if agg:
            lines.append("")
            lines.append(
                "  fleet: qps=%s queue=%s shed=%s err=%s "
                "p50/p95/p99=%s/%s/%s ms step_skew=%s"
                % (_fmt(agg.get("qps")), agg.get("queue_depth"),
                   _fmt(agg.get("shed_ratio")),
                   _fmt(agg.get("error_ratio")),
                   _fmt(lat.get("p50")), _fmt(lat.get("p95")),
                   _fmt(lat.get("p99")),
                   (agg.get("served_step") or {}).get("skew")))
        firing = self.engine.firing()
        pending = [a for a in self.engine.active()
                   if a.state == "pending"]
        lines.append("")
        lines.append("alerts: %d firing, %d pending"
                     % (len(firing), len(pending)))
        for a in firing + pending:
            lines.append("  [%-7s] %s: %s" % (a.state, a.rule,
                                              a.reason))
        hist = self.engine.history()
        if hist:
            lines.append("history (last %d):" % min(len(hist), 10))
            for d in hist[-10:]:
                lines.append("  [%-9s] %s: %s"
                             % (d["state"], d["rule"], d["reason"]))
        return "\n".join(lines)


def _ms(seconds):
    return round(1e3 * seconds, 3) if seconds is not None else None


def _fmt(v):
    return ("%.3g" % v) if v is not None else "-"


def _goodput_skew(ups, threshold=1.25):
    """The PR-14 straggler attribution generalized across processes:
    per-replica goodput windows (scraped off /statusz) -> wall-per-step
    skew, and for each straggler the category whose per-step seconds
    deviate most from the cross-replica median."""
    rows = []
    for rep in ups:
        gp = rep.snapshot.goodput
        if not isinstance(gp, dict) or not gp.get("steps"):
            continue
        cats = {cat: (c.get("per_step_s") or 0.0)
                for cat, c in (gp.get("categories") or {}).items()}
        rows.append({"rank": rep.rank,
                     "per_step_s": gp["wall_s"] / gp["steps"],
                     "categories": cats})
    if len(rows) < 2:
        return None
    walls = sorted(r["per_step_s"] for r in rows)
    median = walls[(len(walls) - 1) // 2]
    skew = (walls[-1] / median) if median else None
    stragglers = [r for r in rows
                  if median and r["per_step_s"] / median > threshold]
    attribution = []
    cat_names = set()
    for r in rows:
        cat_names.update(r["categories"])
    medians = {}
    for cat in cat_names:
        vals = sorted(r["categories"].get(cat, 0.0) for r in rows)
        medians[cat] = vals[(len(vals) - 1) // 2]
    for r in stragglers:
        best = None
        for cat, v in r["categories"].items():
            if cat == "other":
                continue
            ratio = v / max(medians[cat], 1e-9)
            if v > medians[cat] and (best is None
                                     or ratio > best["ratio"]):
                best = {"rank": r["rank"], "category": cat,
                        "per_step_s": round(v, 6),
                        "median_per_step_s": round(medians[cat], 6),
                        "ratio": round(min(ratio, 999.0), 2)}
        if best is not None:
            attribution.append(best)
    return {"max_over_median": round(skew, 4) if skew else None,
            "straggler_ranks": sorted(r["rank"] for r in stragglers),
            "attribution": attribution}


def alertz():
    """The ``/alertz`` payload: the newest registered monitor's engine
    state, or an empty shell when no monitor runs in this process."""
    from . import status as _status
    mon = _status.fleet_monitor()
    if mon is None:
        return {"schema": "mxalertz.v1", "monitors": 0, "firing": [],
                "pending": [], "history": [], "rules": []}
    payload = mon.engine.alertz()
    payload["monitors"] = 1
    payload["fleet"] = mon.fleet_row()
    return payload
