"""Live introspection over HTTP: a stdlib ``http.server`` thread every
serving/training process can run (``MXNET_TPU_OBS_PORT``).

Three endpoints, chosen because they are what fleet tooling already
speaks:

- ``GET /healthz``  -- ``200 READY`` / ``503 NOT_READY`` derived from
  the status board (watcher failure budget, async-writer failures,
  queue saturation); body carries the JSON reasons.
- ``GET /metrics``  -- the existing Prometheus text exposition of the
  live telemetry registry (scrape it; no push gateway).
- ``GET /statusz``  -- the operator JSON: served/published step, swap
  history, bucket occupancy, per-rank last-heartbeat.
- ``GET /alertz``   -- the fleet alert plane (ISSUE 17): firing/pending
  alerts, bounded history, and the active rule set, when a
  :class:`~mxnet_tpu.obs.fleet.FleetMonitor` runs in this process.

When ``MXNET_TPU_OBS_ENDPOINTS_DIR`` is set, :func:`serve` also
publishes this process's ``{pid, rank, generation, port, started_at}``
endpoint file there (atomically, via checkpoint-core) so a
FleetMonitor can discover it; :func:`stop` withdraws it.

Bound to localhost by default (a sidecar/scraper surface, not an
internet listener); ``port=0`` picks an ephemeral port, returned by
:func:`serve` and readable via :func:`port` -- tests and the CI obs
stage use that.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..base import MXNetError
from . import status as _status

__all__ = ["serve", "stop", "port", "running"]

_server = None
_thread = None


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxtpu-obs/1"

    def _send(self, code, body, ctype="application/json"):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                ready, reasons = _status.health()
                self._send(200 if ready else 503,
                           json.dumps({"status": "READY" if ready
                                       else "NOT_READY",
                                       "reasons": reasons}))
            elif path == "/metrics":
                from .. import telemetry as _telemetry
                self._send(200, _telemetry.prom_dump(),
                           ctype="text/plain; version=0.0.4")
            elif path == "/statusz":
                self._send(200, json.dumps(_status.statusz(),
                                           default=str))
            elif path == "/alertz":
                from . import fleet as _fleet
                self._send(200, json.dumps(_fleet.alertz(),
                                           default=str))
            else:
                self._send(404, json.dumps(
                    {"error": "unknown path %r" % path,
                     "paths": ["/healthz", "/metrics", "/statusz",
                               "/alertz"]}))
        except Exception as e:      # an introspection bug must never
            try:                    # kill the serving process
                self._send(500, json.dumps({"error": str(e)}))
            except Exception:
                pass

    def log_message(self, fmt, *args):   # no stderr chatter per scrape
        pass


def serve(port=None, host="127.0.0.1"):
    """Start the introspection server thread; returns the bound port.
    ``port=None`` reads ``MXNET_TPU_OBS_PORT``; ``0`` binds ephemeral.
    Idempotent: an already-running server just reports its port."""
    global _server, _thread
    if _server is not None:
        return _server.server_address[1]
    if port is None:
        from .. import env as _env
        port = int(_env.get("MXNET_TPU_OBS_PORT"))
    srv = ThreadingHTTPServer((host, int(port)), _Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mxtpu-obs-http")
    t.start()
    _server, _thread = srv, t
    bound = srv.server_address[1]
    from . import fleet as _fleet
    _fleet.publish_endpoint(bound)   # no-op unless ENDPOINTS_DIR set
    return bound


def stop():
    """Shut the server down, withdraw the published endpoint, and join
    the thread."""
    global _server, _thread
    srv, _server = _server, None
    t, _thread = _thread, None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if t is not None:
        t.join(timeout=10)
    from . import fleet as _fleet
    _fleet.remove_endpoint()        # the clean-departure path


def port():
    """The bound port, or None when not running."""
    return _server.server_address[1] if _server is not None else None


def running():
    return _server is not None
