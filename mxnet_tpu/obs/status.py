"""Process status board: what `/healthz` and `/statusz` read.

Long-lived components register themselves (weakly -- the board never
extends a lifetime): serving registries, registry watchers, continuous
trainers.  The board derives **readiness** the way a load balancer or
pod manager needs it:

- a :class:`~mxnet_tpu.serving.loop.RegistryWatcher` that exhausted its
  swap failure budget (suspended) means the process is serving a stale
  model and flapping stopped -- NOT_READY until an operator intervenes;
- a failed async checkpoint write (``checkpoint.write_failures``) means
  published state is behind training -- NOT_READY;
- a servable whose bounded queue sits at capacity is shedding load --
  NOT_READY (scale out / back off);
- an elastic restart supervisor whose generation is down (a rank died
  and the relaunch has not landed) or whose restart budget is spent --
  NOT_READY until the world is back or an operator intervenes.

``/statusz`` adds the operator narrative: served vs published step,
recent swap history (the ``serving.swap`` event ring), bucket
occupancy, and per-rank last-heartbeat (the ContinuousTrainer loop
beats once per step; a stale heartbeat is a wedged trainer even when
every thread is technically alive).
"""
from __future__ import annotations

import os
import time
import weakref

__all__ = ["register_watcher", "register_registry", "register_trainer",
           "register_ledger", "register_supervisor", "register_fleet",
           "fleet_monitor", "heartbeat", "health", "statusz", "reset",
           "STATUSZ_SCHEMA"]

# The /statusz contract version (ISSUE 17).  The fleet scrape client
# refuses to parse any other value -- bump on incompatible change.
STATUSZ_SCHEMA = "mxstatusz.v1"

_watchers = weakref.WeakSet()
_registries = weakref.WeakSet()
_trainers = weakref.WeakSet()
_ledgers = weakref.WeakSet()    # goodput StepLedgers (obs.goodput)
_supervisors = weakref.WeakSet()   # elastic restart supervisors
_fleet = weakref.WeakSet()      # FleetMonitors (obs.fleet)
_heartbeats = {}                # rank -> wall time of last beat


def _rank():
    try:
        return int(os.environ.get("MXNET_TPU_PROC_ID", "0") or 0)
    except ValueError:
        return 0


def _generation():
    try:
        return int(os.environ.get("MXNET_TPU_GENERATION", "0") or 0)
    except ValueError:
        return 0


def register_watcher(watcher):
    _watchers.add(watcher)


def register_registry(registry):
    _registries.add(registry)


def register_trainer(trainer):
    _trainers.add(trainer)


def register_ledger(ledger):
    _ledgers.add(ledger)


def register_supervisor(supervisor):
    _supervisors.add(supervisor)


def register_fleet(monitor):
    _fleet.add(monitor)


def fleet_monitor():
    """The newest registered FleetMonitor (``/alertz`` reads it), or
    None when this process runs no fleet plane."""
    best = None
    for m in list(_fleet):
        best = m
    return best


def heartbeat(rank=None):
    """One liveness beat (the trainer loop calls this every step)."""
    _heartbeats[_rank() if rank is None else int(rank)] = time.time()


def reset():
    """Drop every registration (tests)."""
    _watchers.clear()
    _registries.clear()
    _trainers.clear()
    _ledgers.clear()
    _supervisors.clear()
    _fleet.clear()
    _heartbeats.clear()


def _counter_value(name):
    from .. import telemetry as _telemetry
    inst = _telemetry.registry().get(name)
    return inst.value if inst is not None else 0


def health():
    """``(ready, reasons)``: ready is True iff reasons is empty."""
    reasons = []
    for w in list(_watchers):
        try:
            if w.suspended:
                reasons.append("watcher_suspended:%s" % w.name)
        except Exception:
            continue
    failures = _counter_value("checkpoint.write_failures")
    if failures:
        reasons.append("checkpoint_write_failures:%d" % failures)
    for s in list(_supervisors):
        try:
            if s.exhausted:
                reasons.append("restart_budget_exhausted:%d"
                               % s.generation)
            elif s.generation_down:
                reasons.append("generation_down:%d" % s.generation)
        except Exception:
            continue
    for reg in list(_registries):
        try:
            names = reg.names()
        except Exception:
            continue
        for name in names:
            try:
                s = reg.servable(name)
                if s.queue_depth() >= s.queue_capacity:
                    reasons.append("queue_saturated:%s" % name)
            except Exception:
                continue
    return (not reasons), reasons


def statusz():
    """The full operator snapshot (JSON-ready)."""
    from .. import telemetry as _telemetry
    reg = _telemetry.registry()
    watchers = []
    for w in list(_watchers):
        try:
            watchers.append({"name": w.name,
                             "served_step": w.served_step,
                             "suspended": w.suspended,
                             "bad_steps": w.bad_steps()})
        except Exception:
            continue
    trainers = []
    for t in list(_trainers):
        try:
            trainers.append({"step": t.step,
                             "published_step": t.published_step})
        except Exception:
            continue
    servables = []
    for r in list(_registries):
        try:
            names = r.names()
        except Exception:
            continue
        for name in names:
            try:
                s = r.servable(name)
                servables.append({"name": name,
                                  "queue_depth": s.queue_depth(),
                                  "queue_capacity": s.queue_capacity,
                                  "buckets": list(s.buckets)})
            except Exception:
                continue
    supervisors = []
    for s in list(_supervisors):
        try:
            supervisors.append({"generation": s.generation,
                                "restarts": s.restarts,
                                "down": s.generation_down,
                                "exhausted": s.exhausted})
        except Exception:
            continue
    goodput = None
    for led in list(_ledgers):
        try:
            win = led.last()
        except Exception:
            continue
        if win is not None:
            goodput = win       # newest registered ledger wins
    try:
        from ..analysis import numerics as _numerics
        numerics_row = _numerics.status_row()
    except Exception:
        numerics_row = None
    try:
        from ..analysis import memory as _memory
        memory_row = _memory.status_row()
    except Exception:
        memory_row = None
    fleet_row = None
    mon = fleet_monitor()
    if mon is not None:
        try:
            fleet_row = mon.fleet_row()
        except Exception:
            fleet_row = None
    swap_ev = reg.get("serving.swap")
    occupancy = reg.get("serving.batch_occupancy")
    served = reg.get("serving.served_step")
    published = reg.get("train_loop.published_step")
    ready, reasons = health()
    return {
        "schema": STATUSZ_SCHEMA,
        "pid": os.getpid(),
        "rank": _rank(),
        "generation": _generation(),
        "time": time.time(),
        "ready": ready,
        "not_ready_reasons": reasons,
        "served_step": served.value if served is not None else None,
        "published_step": (published.value if published is not None
                           else None),
        "watchers": watchers,
        "trainers": trainers,
        "servables": servables,
        "supervisors": supervisors,
        "swap_history": swap_ev.recent if swap_ev is not None else [],
        "bucket_occupancy": (occupancy.snapshot()
                             if occupancy is not None else None),
        "goodput": goodput,     # latest StepLedger window (obs.goodput)
        # the non-finite sentinel: armed?, checks run, nonfinite steps
        # seen, last attribution (analysis.numerics, docs/numerics.md)
        "numerics": numerics_row,
        # the live-buffer leak sentinel: armed?, censuses run, live
        # totals, leaks flagged (analysis.memory, docs/memory.md)
        "memory": memory_row,
        "heartbeats": dict(_heartbeats),
        # replicas up/down + firing-alert count when a FleetMonitor
        # runs here (obs.fleet, ISSUE 17)
        "fleet": fleet_row,
    }
