"""Declarative SLO alerting for the fleet plane (ISSUE 17).

A :class:`Rule` names a fleet metric (``p99_latency_ms``,
``shed_ratio``, ``error_ratio``, ``replica_down``), a threshold, and
TWO burn-rate windows -- the multi-window discipline SRE paging uses:
the **fast** window makes the alert responsive, the **slow** window
makes it credible, and only when BOTH burn does the alert fire, so a
single slow round trip can never page.  Each alert is a typed state
machine::

    ok -> pending   (fast window burning)
       -> firing    (fast AND slow windows burning; reason names the
                     replica/rank/generation that caused it)
       -> resolved  (no breach for resolve_s -- sustained recovery,
                     not one lucky sample)
       -> ok        (after holddown_s, bounding flap frequency)

``replica_down`` uses zero-length windows by design: a dead replica is
not a statistical claim, so it fires within one scrape round and
resolves the moment the rank is healthy again (the supervisor-relaunch
contract CI's ``fleet`` stage proves).

Resolved alerts land in a bounded history ring; the engine publishes
``fleet.alerts_firing`` + a ``fleet.alert`` event per transition when
telemetry is enabled, and ``/alertz`` (obs.server) renders the whole
thing.  Rules are overridable per deployment via
``MXNET_TPU_OBS_ALERT_RULES`` (JSON list of rule dicts, merged onto
the defaults by name).
"""
from __future__ import annotations

import json
import os
import time
from collections import deque

from .. import sync as _sync
from ..base import MXNetError

__all__ = ["Rule", "Alert", "AlertEngine", "default_rules",
           "parse_rules", "METRICS"]

# The metrics the engine knows how to judge.  ``replica_down`` is a
# count (breach when > threshold); the ratios/latency are floats.
METRICS = ("p99_latency_ms", "shed_ratio", "error_ratio",
           "replica_down")

_HISTORY = 256          # bounded ring of resolved/cancelled alerts


class Rule:
    """One declarative SLO rule.  ``metric`` defaults to ``name`` so
    the four stock rules read naturally; a tuned deployment may carry
    several rules over one metric under distinct names."""

    __slots__ = ("name", "metric", "threshold", "fast_s", "slow_s",
                 "fast_burn", "slow_burn", "resolve_s", "holddown_s")

    def __init__(self, name, threshold, metric=None, fast_s=30.0,
                 slow_s=300.0, fast_burn=0.5, slow_burn=0.5,
                 resolve_s=60.0, holddown_s=60.0):
        metric = name if metric is None else metric
        if metric not in METRICS:
            raise MXNetError(
                "alert rule %r: unknown metric %r (known: %s)"
                % (name, metric, ", ".join(METRICS)))
        if fast_s > slow_s:
            raise MXNetError(
                "alert rule %r: fast window (%gs) must not exceed the "
                "slow window (%gs)" % (name, fast_s, slow_s))
        self.name = name
        self.metric = metric
        self.threshold = float(threshold)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.resolve_s = float(resolve_s)
        self.holddown_s = float(holddown_s)

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return ("Rule(%s: %s > %g, fast %gs@%.0f%%, slow %gs@%.0f%%)"
                % (self.name, self.metric, self.threshold, self.fast_s,
                   100 * self.fast_burn, self.slow_s,
                   100 * self.slow_burn))


def default_rules():
    """The stock rule set (thresholds are deliberately conservative;
    tune per deployment via MXNET_TPU_OBS_ALERT_RULES)."""
    return [
        Rule("p99_latency_ms", threshold=500.0),
        Rule("shed_ratio", threshold=0.05),
        Rule("error_ratio", threshold=0.02),
        # a dead replica is a fact, not a trend: zero-length windows
        # fire within one scrape round; resolve_s=0 resolves on the
        # first healthy round after the relaunch lands
        Rule("replica_down", threshold=0.0, fast_s=0.0, slow_s=0.0,
             resolve_s=0.0, holddown_s=0.0),
    ]


def parse_rules(spec=None):
    """Rules from a JSON spec (``MXNET_TPU_OBS_ALERT_RULES`` when
    ``spec`` is None): a list of rule dicts merged ONTO the defaults by
    name -- override a stock threshold/window, or add a new named rule
    over a known metric.  Empty/unset spec returns the defaults; an
    unparseable spec raises loudly (a silently-ignored alert config is
    the worst possible failure mode for an alerting plane)."""
    if spec is None:
        spec = os.environ.get("MXNET_TPU_OBS_ALERT_RULES", "")
    rules = {r.name: r for r in default_rules()}
    if not spec or not str(spec).strip():
        return list(rules.values())
    try:
        overrides = json.loads(spec) if isinstance(spec, str) else spec
    except ValueError as e:
        raise MXNetError("MXNET_TPU_OBS_ALERT_RULES is not valid "
                         "JSON: %s" % e) from e
    if not isinstance(overrides, list):
        raise MXNetError("MXNET_TPU_OBS_ALERT_RULES must be a JSON "
                         "list of rule dicts, got %r" % type(overrides))
    for d in overrides:
        if not isinstance(d, dict) or "name" not in d:
            raise MXNetError("alert rule spec needs a 'name': %r" % (d,))
        name = d["name"]
        base = rules.get(name)
        merged = base.as_dict() if base is not None else {}
        unknown = set(d) - set(Rule.__slots__)
        if unknown:
            raise MXNetError("alert rule %r: unknown field(s) %s"
                             % (name, ", ".join(sorted(unknown))))
        merged.update(d)
        if "threshold" not in merged:
            raise MXNetError("alert rule %r needs a threshold" % name)
        rules[name] = Rule(**merged)
    return list(rules.values())


class Alert:
    """One alert instance walking pending -> firing -> resolved."""

    __slots__ = ("rule", "metric", "state", "reason", "value",
                 "threshold", "pending_since", "fired_at",
                 "resolved_at")

    def __init__(self, rule, value, reason, now):
        self.rule = rule.name
        self.metric = rule.metric
        self.threshold = rule.threshold
        self.state = "pending"
        self.value = value
        self.reason = reason
        self.pending_since = now
        self.fired_at = None
        self.resolved_at = None

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return "Alert(%s %s: %s)" % (self.rule, self.state, self.reason)


class AlertEngine:
    """Evaluate a rule set over a stream of fleet metric samples.

    ``observe(values, detail=None, now=None)`` takes one scrape round's
    metric values (``{metric: float-or-None}``; None = no data this
    round, which contributes NO observation -- silence is not health)
    plus optional per-metric detail strings that land in the alert
    reason (``replica_down`` detail names rank/generation/pid).
    Thread-safe; the FleetMonitor calls it from its poll thread and
    ``/alertz`` reads it from HTTP handler threads.
    """

    def __init__(self, rules=None, history=_HISTORY):
        self.rules = list(rules) if rules is not None else parse_rules()
        self._lock = _sync.Lock(name="obs.alert_engine")
        self._obs = {r.name: deque() for r in self.rules}
        self._active = {}           # rule name -> Alert (pending|firing)
        self._holddown = {}         # rule name -> ok-again time
        self._history = deque(maxlen=int(history))
        self._transitions = 0

    # -- evaluation ----------------------------------------------------
    def observe(self, values, detail=None, now=None):
        """Fold one round of metric values; returns the list of alerts
        that TRANSITIONED this round (new pending, fired, resolved)."""
        now = time.time() if now is None else float(now)
        detail = detail or {}
        changed = []
        with self._lock:
            for rule in self.rules:
                value = values.get(rule.metric)
                if value is None:
                    continue
                ring = self._obs[rule.name]
                breach = float(value) > rule.threshold
                ring.append((now, breach))
                horizon = now - max(rule.slow_s, rule.resolve_s) - 1.0
                while ring and ring[0][0] < horizon:
                    ring.popleft()
                changed.extend(
                    self._step_rule(rule, value, breach,
                                    detail.get(rule.metric), now))
        for alert in changed:
            self._publish(alert)
        return changed

    def _burn(self, rule, window_s, now):
        """Breach fraction over the trailing window (None = no
        observations in the window).  A zero-length window judges only
        observations from this instant -- the replica_down case."""
        ring = self._obs[rule.name]
        if window_s <= 0:
            obs = [b for (t, b) in ring if t >= now]
        else:
            obs = [b for (t, b) in ring if t >= now - window_s]
        if not obs:
            return None
        return sum(1 for b in obs if b) / len(obs)

    def _step_rule(self, rule, value, breach, detail, now):
        # under self._lock
        changed = []
        alert = self._active.get(rule.name)
        fast = self._burn(rule, rule.fast_s, now)
        slow = self._burn(rule, rule.slow_s, now)
        if alert is None:
            if now < self._holddown.get(rule.name, 0.0):
                return changed
            if breach and fast is not None and fast >= rule.fast_burn:
                alert = Alert(rule, value,
                              self._reason(rule, value, detail), now)
                self._active[rule.name] = alert
                changed.append(alert)
        if alert is None:
            return changed
        if alert.state == "pending":
            if fast is not None and fast >= rule.fast_burn \
                    and slow is not None and slow >= rule.slow_burn:
                # BOTH windows burn: the multi-window page condition
                alert.state = "firing"
                alert.fired_at = now
                alert.value = value
                alert.reason = self._reason(rule, value, detail)
                if alert not in changed:
                    changed.append(alert)
            elif fast is not None and fast < rule.fast_burn:
                # the blip passed before the slow window agreed:
                # cancel without ever paging
                alert.state = "cancelled"
                alert.resolved_at = now
                del self._active[rule.name]
                self._history.append(alert.as_dict())
                changed.append(alert)
        elif alert.state == "firing":
            if breach:
                alert.value = value
                alert.reason = self._reason(rule, value, detail)
            else:
                last_breach = max((t for (t, b) in self._obs[rule.name]
                                   if b), default=None)
                clean_for = now - last_breach \
                    if last_breach is not None else float("inf")
                if clean_for >= rule.resolve_s:
                    alert.state = "resolved"
                    alert.resolved_at = now
                    alert.reason += " | recovered%s" % (
                        " (%s)" % detail if detail else "")
                    del self._active[rule.name]
                    self._history.append(alert.as_dict())
                    self._holddown[rule.name] = now + rule.holddown_s
                    changed.append(alert)
        return changed

    @staticmethod
    def _reason(rule, value, detail):
        head = "%s %.4g > %.4g" % (rule.metric, float(value),
                                   rule.threshold)
        return "%s: %s" % (head, detail) if detail else head

    def _publish(self, alert):
        from .. import telemetry as _telemetry
        if not _telemetry._ENABLED:
            return
        _telemetry.hooks.fleet_alert(alert.rule, alert.state,
                                     alert.reason, alert.value)
        _telemetry.hooks.fleet_alerts_firing(len(self.firing()))

    # -- read side -----------------------------------------------------
    def firing(self):
        with self._lock:
            return [a for a in self._active.values()
                    if a.state == "firing"]

    def active(self):
        """Pending + firing alerts."""
        with self._lock:
            return list(self._active.values())

    def history(self):
        """Resolved/cancelled alerts, oldest first (bounded ring)."""
        with self._lock:
            return list(self._history)

    def alertz(self):
        """The ``/alertz`` payload."""
        with self._lock:
            return {
                "schema": "mxalertz.v1",
                "firing": [a.as_dict() for a in self._active.values()
                           if a.state == "firing"],
                "pending": [a.as_dict() for a in self._active.values()
                            if a.state == "pending"],
                "history": list(self._history),
                "rules": [r.as_dict() for r in self.rules],
            }
