"""Goodput ledger (ISSUE 14 tentpole): online step-time attribution,
a rolling MFU gauge, and a regression sentinel.

Every instrument this needs already exists -- PR 6's per-dispatch step
walls (``profiling.step_time``), PR 4's feed starvation timers
(``feed.consumer_wait``), PR 2/3's host-sync and checkpoint timers,
PR 13's ``env.*`` health gauges -- but nothing reconciled them into a
per-window accounting, so "where does the step time go" was answered by
hand-reading counters (and r05's tunnel collapse read as a perf
regression for a whole bench round).  :class:`StepLedger` is that
reconciliation: per rolling window of training steps it decomposes the
window's wall clock into named categories (the goodput/badput
discipline of large-scale training stacks):

============================  =======================================
category                      source (telemetry instrument deltas)
============================  =======================================
``device_compute``            ``profiling.step_time`` (compiled
                              TrainStep dispatch walls) +
                              ``trainer.step_time`` (eager
                              Trainer.step; the two never cover the
                              same step -- a compiled TrainStep folds
                              the update in-graph)
``input_wait``                ``feed.consumer_wait`` +
                              ``data.wait_time``
``host_sync``                 ``dispatch.host_sync_time`` (asnumpy /
                              wait_to_read / waitall walls)
``checkpoint_stall``          ``checkpoint.save_time`` +
                              ``checkpoint.async_wait``
``recompile``                 ``compile.build_time``
``other``                     the un-attributed remainder
============================  =======================================

**Reconciliation contract** (the PR-6 categories-sum-to-totals
discipline, applied to wall clock): every window's categories sum to
the window wall within ``tol`` -- ``other`` absorbs un-instrumented
time, so the only way the contract can fail is *overshoot* (attributed
time exceeding wall, i.e. double counting or a cross-thread overlap),
which is exactly the accounting bug the contract exists to catch.  CI
gates ``reconciliation["ok"]`` on every window (ci/run_all.sh obs).

**MFU gauge**: given flops-per-step (the compiled executable's cost
report -- ``TrainStep.cost_analysis()["flops"]``), each window
publishes ``window_flops / wall / device_peak`` as the ``goodput.mfu``
gauge (device peak from ``profiling.roofline.device_peaks``).

**Regression sentinel**: per category, an EWMA baseline of per-step
seconds plus an EWMA of absolute deviation (a MAD analog).  A window
whose per-step category time exceeds ``mean + mad_k * dev`` (and moves
at least 5% of the window wall -- jitter on a near-zero category is
not a regression) emits a ``goodput.regression`` event NAMING the
category.  Two guards, both lessons from real rounds:

- the **env guard** (the r05 lesson): when the ``env.*`` health gauges
  say the tunnel is degraded (``env.dispatch_roundtrip_us`` past
  :data:`DEGRADED_RTT_US` -- the same threshold bench.py derives its
  ``degraded_env`` flag from), the window is reported as
  ``goodput.env_degraded`` and NOT as a regression, and the baseline
  is not updated (degraded windows would poison it);
- the **publish guard**: a window spanning a checkpoint publish
  (``note_publish``) expects a ``checkpoint_stall`` spike -- expected
  work, not a regression.

Gate: ``MXNET_TPU_OBS_GOODPUT=1`` / ``obs.enable_goodput()`` arms the
loop hooks (ContinuousTrainer steps the process ledger); disabled, the
instrumented sites pay one module-flag check, the same contract as
``telemetry._ENABLED``.  The ledger itself reads telemetry instruments,
so ``MXNET_TPU_TELEMETRY=1`` must also be on for non-empty categories.
"""
from __future__ import annotations

import os
import time

__all__ = ["CATEGORIES", "DEGRADED_RTT_US", "StepLedger", "ledger",
           "reset", "env_degraded", "line_summary"]

# attribution categories, in report order ("other" is the remainder)
CATEGORIES = ("device_compute", "input_wait", "host_sync",
              "checkpoint_stall", "recompile", "other")

# timer instruments whose .sum deltas feed each named category
_CATEGORY_TIMERS = {
    "device_compute": ("profiling.step_time", "trainer.step_time"),
    "input_wait": ("feed.consumer_wait", "data.wait_time"),
    "host_sync": ("dispatch.host_sync_time",),
    "checkpoint_stall": ("checkpoint.save_time", "checkpoint.async_wait"),
    "recompile": ("compile.build_time",),
}

# THE degraded-environment threshold: dispatch round trips slower than
# this mean the tunnel, not the model (r05: ~90ms vs ~2ms healthy).
# bench.py derives its per-line `degraded_env` flag from the same
# number, so the sentinel's env guard and the bench flag cannot
# disagree (contract-locked in tests/test_bench_contract.py).
DEGRADED_RTT_US = 10000.0

# a category must move at least this share of the window wall before
# the sentinel may call it a regression (absolute significance floor)
_MIN_MOVE_FRAC = 0.05


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_degraded(rtt_us=None):
    """The sentinel's env guard: True when the dispatch round trip says
    the environment (tunnel), not the workload, is slow.  With no
    argument, reads the live ``env.dispatch_roundtrip_us`` gauge (set
    by bench.py's health probe via ``hooks.env_health``); unknown
    (gauge never set) reads healthy."""
    if rtt_us is None:
        from .. import telemetry as _telemetry
        g = _telemetry.registry().get("env.dispatch_roundtrip_us")
        rtt_us = g.value if g is not None else None
    return bool(rtt_us is not None and rtt_us > DEGRADED_RTT_US)


def line_summary(window):
    """The compact breakdown a bench JSONL line carries: shares +
    verdict + MFU, no baselines or raw deltas."""
    if window is None:
        return None
    return {
        "steps": window["steps"],
        "wall_s": round(window["wall_s"], 4),
        "mfu": window["mfu"],
        "shares": {cat: round(c["share"], 4)
                   for cat, c in window["categories"].items()},
        "verdict": window["verdict"]["detail"],
        "bound": window["verdict"]["bound"],
        "reconciled": window["reconciliation"]["ok"],
        "env_degraded": window["env_degraded"],
    }


class StepLedger:
    """Online per-window wall-time attribution over the telemetry
    instruments.

    ::

        ledger = StepLedger(window_steps=20)
        for batch in feed:
            train(batch)
            ledger.step()          # closes a window every 20 steps
        last = ledger.flush()      # close the partial tail window

    The ledger never touches a device and never blocks: ``step()`` is
    a counter bump until a window boundary, where closing a window is
    a handful of instrument reads.  Windows land in a bounded local
    ring (:meth:`windows`) and -- when telemetry is enabled -- publish
    as ``goodput.*`` gauges/timers/events so Prometheus, /statusz, and
    the summarize CLI all see them.
    """

    def __init__(self, window_steps=None, tol=None, mad_k=None,
                 ewma_alpha=0.3, min_baseline=3, history=64,
                 flops_per_step=None, registry=None):
        from .. import sync as _sync
        self.window_steps = int(window_steps if window_steps is not None
                                else _env_float(
                                    "MXNET_TPU_OBS_GOODPUT_WINDOW", 20))
        if self.window_steps < 1:
            self.window_steps = 1
        self.tol = float(tol if tol is not None else _env_float(
            "MXNET_TPU_OBS_GOODPUT_TOL", 0.25))
        self.mad_k = float(mad_k if mad_k is not None else _env_float(
            "MXNET_TPU_OBS_GOODPUT_MAD_K", 4.0))
        self.ewma_alpha = float(ewma_alpha)
        self.min_baseline = int(min_baseline)
        self.flops_per_step = flops_per_step
        self._registry = registry
        self._history = int(history)
        self._windows = []
        self._index = 0
        self._baseline = {}     # category -> {"mean", "dev", "n"}
        self._lock = _sync.Lock(name="obs.goodput")
        with self._lock:
            self._open_window()

    # -- instrument reads ----------------------------------------------
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from .. import telemetry as _telemetry
        return _telemetry.registry()

    def _timer_sums(self):
        reg = self._reg()
        sums = {}
        for names in _CATEGORY_TIMERS.values():
            for name in names:
                t = reg.get(name)
                sums[name] = float(t.sum) if t is not None else 0.0
        return sums

    # -- window lifecycle ----------------------------------------------
    def _open_window(self):
        # under self._lock
        self._t0 = time.perf_counter()
        self._sums0 = self._timer_sums()
        self._steps = 0
        self._publishes = 0

    def step(self, n=1):
        """Record ``n`` completed training steps; closes (and returns)
        a window at every ``window_steps`` boundary, else None."""
        with self._lock:
            self._steps += int(n)
            if self._steps < self.window_steps:
                return None
            return self._close("steps")

    def note_publish(self):
        """Mark that the current window spans a checkpoint publish --
        its ``checkpoint_stall`` spike is expected work, and the
        sentinel must not read it as a regression."""
        with self._lock:
            self._publishes += 1

    def flush(self, reason="flush"):
        """Close the current window regardless of step count (the
        serving-only / end-of-bench surface; a zero-step window
        reports ``idle`` and runs no sentinel)."""
        with self._lock:
            return self._close(reason)

    def windows(self):
        """Recent window reports, oldest first (bounded ring)."""
        with self._lock:
            return list(self._windows)

    def last(self):
        with self._lock:
            return self._windows[-1] if self._windows else None

    # -- the close: attribution, reconciliation, MFU, sentinel ---------
    def _close(self, reason):
        # under self._lock
        wall = max(time.perf_counter() - self._t0, 0.0)
        sums1 = self._timer_sums()
        steps, publishes = self._steps, self._publishes
        seconds = {}
        for cat, names in _CATEGORY_TIMERS.items():
            seconds[cat] = sum(
                max(sums1[n] - self._sums0.get(n, 0.0), 0.0)
                for n in names)
        known = sum(seconds.values())
        seconds["other"] = max(wall - known, 0.0)
        total = known + seconds["other"]
        err = ((total - wall) / wall) if wall > 0 else 0.0
        categories = {}
        for cat in CATEGORIES:
            s = seconds[cat]
            categories[cat] = {
                "seconds": round(s, 6),
                "share": (s / wall) if wall > 0 else 0.0,
                "per_step_s": (s / steps) if steps else None,
            }
        g = self._reg().get("env.dispatch_roundtrip_us")
        rtt_us = g.value if g is not None else None
        report = {
            "index": self._index,
            "reason": reason,
            "steps": steps,
            "publishes": publishes,
            "wall_s": wall,
            "categories": categories,
            "reconciliation": {"sum_s": round(total, 6),
                               "wall_s": round(wall, 6),
                               "error": round(err, 6), "tol": self.tol,
                               "ok": err <= self.tol},
            "mfu": None,
            "flops": None,
            "verdict": _verdict(categories, steps, wall),
            "regressions": [],
            "env_degraded": bool(rtt_us is not None
                                 and rtt_us > DEGRADED_RTT_US),
            "dispatch_roundtrip_us": rtt_us,
        }
        self._attach_mfu(report)
        self._sentinel(report)
        self._index += 1
        self._windows.append(report)
        if len(self._windows) > self._history:
            del self._windows[0]
        self._publish(report)
        self._open_window()
        return report

    def _attach_mfu(self, report):
        fps = self.flops_per_step
        if callable(fps):
            try:
                fps = fps()
            except Exception:
                fps = None
        steps, wall = report["steps"], report["wall_s"]
        if not fps or not steps or wall <= 0:
            return
        from ..profiling import roofline
        peak, _bw, assumed = roofline.device_peaks()
        flops = float(fps) * steps
        report["flops"] = flops
        report["mfu"] = round(flops / wall / peak, 4)
        report["peaks_assumed"] = assumed

    def _sentinel(self, report):
        steps, wall = report["steps"], report["wall_s"]
        if not steps or wall <= 0:
            return                    # idle window: nothing to judge
        if report["env_degraded"]:
            # the r05 lesson: a degraded tunnel is ENVIRONMENT, not a
            # model regression -- report it as such and keep the
            # baseline clean of degraded samples
            return
        floor = _MIN_MOVE_FRAC * wall / steps
        for cat in CATEGORIES:
            if cat == "other":
                continue
            x = report["categories"][cat]["per_step_s"]
            base = self._baseline.get(cat)
            if base is not None and base["n"] >= self.min_baseline:
                thresh = base["mean"] + self.mad_k * max(
                    base["dev"], 0.1 * base["mean"], 1e-6)
                moved = x - base["mean"]
                if x > thresh and moved >= floor and not (
                        cat == "checkpoint_stall"
                        and report["publishes"]):
                    report["regressions"].append({
                        "category": cat,
                        "per_step_s": round(x, 6),
                        "baseline_per_step_s": round(base["mean"], 6),
                        "ratio": round(x / base["mean"], 2)
                        if base["mean"] > 0 else None,
                    })
            # EWMA baseline update (mean + absolute-deviation MAD
            # analog); regressed windows update too -- a sustained
            # shift becomes the new normal instead of alerting forever.
            # Publish windows keep their EXPECTED checkpoint_stall
            # spike out of the baseline (it would mask a real stall).
            if cat == "checkpoint_stall" and report["publishes"]:
                continue
            if base is None:
                self._baseline[cat] = {"mean": x, "dev": 0.0, "n": 1}
            else:
                a = self.ewma_alpha
                base["dev"] = (1 - a) * base["dev"] \
                    + a * abs(x - base["mean"])
                base["mean"] = (1 - a) * base["mean"] + a * x
                base["n"] += 1

    def _publish(self, report):
        from .. import telemetry as _telemetry
        if not _telemetry._ENABLED:
            return
        _telemetry.hooks.goodput_window(report)
        if report["env_degraded"] and report["steps"]:
            _telemetry.hooks.goodput_env_degraded(
                report["index"], report["dispatch_roundtrip_us"])
        for r in report["regressions"]:
            _telemetry.hooks.goodput_regression(
                r["category"], r["per_step_s"],
                r["baseline_per_step_s"], r["ratio"], report["index"])

    def baseline(self):
        """Copy of the sentinel's per-category EWMA state (tests)."""
        with self._lock:
            return {k: dict(v) for k, v in self._baseline.items()}


def _verdict(categories, steps, wall):
    """The bottleneck verdict: one operator-readable sentence per
    window (the summarize CLI's headline line)."""
    if not steps or wall <= 0:
        return {"bound": "idle",
                "detail": "idle: no training steps in window"}
    sec = {c: categories[c]["seconds"] for c in CATEGORIES}
    share = {c: categories[c]["share"] for c in CATEGORIES}
    dc, iw = sec["device_compute"], sec["input_wait"]
    if iw > 0 and iw >= 0.5 * dc and share["input_wait"] >= 0.15:
        # "the feed supplies N% of device demand": of the time the
        # device could have been computing, how much it actually was
        supply = dc / (dc + iw) if (dc + iw) > 0 else 0.0
        return {"bound": "input",
                "detail": "input-bound: feed supplies %d%% of device "
                          "demand" % int(round(100 * supply))}
    for cat, bound in (("recompile", "recompile"),
                       ("checkpoint_stall", "checkpoint"),
                       ("host_sync", "host-sync")):
        if share[cat] >= 0.2:
            return {"bound": bound,
                    "detail": "%s-bound: %s takes %d%% of window wall"
                              % (bound, cat,
                                 int(round(100 * share[cat])))}
    if share["device_compute"] >= 0.5:
        return {"bound": "compute",
                "detail": "compute-bound: device busy %d%% of wall"
                          % int(round(100 * share["device_compute"]))}
    top = max((c for c in CATEGORIES if c != "other"),
              key=lambda c: sec[c])
    return {"bound": "mixed",
            "detail": "mixed: top category %s at %d%% of wall "
                      "(other %d%%)"
                      % (top, int(round(100 * share[top])),
                         int(round(100 * share["other"])))}


# -- the process ledger (what the ContinuousTrainer hooks drive) -------
_LEDGER = None


def ledger(**kwargs):
    """Get-or-create the process StepLedger (registered on the status
    board so /statusz carries the latest window)."""
    global _LEDGER
    if _LEDGER is None:
        _LEDGER = StepLedger(**kwargs)
        from . import status
        status.register_ledger(_LEDGER)
    return _LEDGER


def reset():
    """Drop the process ledger (tests)."""
    global _LEDGER
    _LEDGER = None
