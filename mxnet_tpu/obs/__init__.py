"""Ops plane for the always-on loop (ISSUE 13): request/step tracing,
a crash-safe flight recorder, and live HTTP introspection.

``mx.telemetry`` (counters/histograms) says *how much*; ``mx.obs`` says
*which one and why*:

- **tracing** (``obs.trace``): context-propagated trace/span IDs
  threaded through the serving path (submit -> queue wait -> batch
  assembly -> compiled dispatch -> device_get -> respond, batcher
  fan-in recorded as span links) and the training loop (step ->
  publish -> checkpoint commit -> watcher discover -> warm -> install),
  exported as Chrome-trace JSON and streamed into the telemetry JSONL;
- **flight recorder** (``obs.flight``): a bounded mmap'd ring of the
  last records that survives ``os._exit``/SIGKILL, dumped automatically
  from the preemption handler, the chaos KILL path, and a SIGUSR2
  stack-snapshot hook; render with ``mxtelemetry blackbox <file>``;
- **introspection** (``obs.server``): ``/healthz`` (watcher failure
  budget + writer errors + queue saturation), ``/metrics`` (Prometheus
  exposition), ``/statusz`` (served/published step, swap history,
  heartbeats) on ``MXNET_TPU_OBS_PORT``;
- **goodput ledger** (``obs.goodput``, ISSUE 14): per-window step-time
  attribution (device_compute / input_wait / host_sync /
  checkpoint_stall / recompile / other, reconciled to window wall),
  a rolling MFU gauge, and an EWMA+MAD regression sentinel guarded by
  the env.* health gauges; armed by ``MXNET_TPU_OBS_GOODPUT=1`` /
  ``obs.enable_goodput()``;
- **fleet plane** (``obs.fleet`` + ``obs.alerts``, ISSUE 17): endpoint
  discovery via ``MXNET_TPU_OBS_ENDPOINTS_DIR`` (atomic publish,
  dead-pid sweep), a scrape client + :class:`~mxnet_tpu.obs.fleet.\
FleetMonitor` aggregating /healthz //metrics //statusz across replicas
  (merged latency histograms -- never averaged p99s), and a burn-rate
  SLO :class:`~mxnet_tpu.obs.alerts.AlertEngine` behind ``/alertz``
  and ``mxtelemetry fleet``.

Tracing is gated exactly like telemetry: disabled (the default), every
instrumented site pays ONE module-flag check (``obs._TRACE_ENABLED``)
and makes zero calls into ``obs.trace`` -- proven by
tests/test_obs.py::test_tracing_disabled_makes_zero_trace_calls.
Enable with ``MXNET_TPU_OBS_TRACE=1`` or ``obs.enable_tracing()``.
"""
from __future__ import annotations

import os

from . import alerts, flight, goodput, status, trace
from .trace import (TraceContext, begin_span, current, end_span,
                    export_chrome_trace, record_span, span, spans)
from .trace import trace as start_trace

__all__ = [
    "enable_tracing", "disable_tracing", "tracing_enabled",
    "enable_goodput", "disable_goodput", "goodput_enabled",
    "start_trace", "span", "begin_span", "end_span", "record_span",
    "current", "spans", "export_chrome_trace", "TraceContext",
    "flight", "goodput", "status", "server", "serve",
    "install_blackbox", "fleet", "alerts",
]

# THE flag every traced hot path checks (one module-attribute read).
# Mutate only through enable_tracing()/disable_tracing().
_TRACE_ENABLED = False

# THE flag the goodput-ledger hook sites check (ContinuousTrainer's
# step/publish loop); same zero-overhead contract as _TRACE_ENABLED.
_GOODPUT_ENABLED = False


def enable_tracing():
    """Arm the trace hooks (idempotent)."""
    global _TRACE_ENABLED
    _TRACE_ENABLED = True


def disable_tracing():
    """Disarm the trace hooks; recorded spans are kept."""
    global _TRACE_ENABLED
    _TRACE_ENABLED = False


def tracing_enabled():
    return _TRACE_ENABLED


def enable_goodput():
    """Arm the goodput-ledger loop hooks (idempotent; the ledger reads
    telemetry instruments, so enable telemetry too for non-empty
    category attribution)."""
    global _GOODPUT_ENABLED
    _GOODPUT_ENABLED = True


def disable_goodput():
    """Disarm the goodput hooks; recorded windows are kept."""
    global _GOODPUT_ENABLED
    _GOODPUT_ENABLED = False


def goodput_enabled():
    return _GOODPUT_ENABLED


def install_blackbox(path=None, capacity=None):
    """Install the process flight recorder (see ``obs.flight``)."""
    return flight.install(path, capacity=capacity)


def serve(port=None):
    """Start the introspection HTTP server (see ``obs.server``)."""
    from . import server as _server
    return _server.serve(port)


from . import server  # noqa: E402  (handler imports status above)
from . import fleet  # noqa: E402  (imports alerts + sync above)

# env arming (same != "0" convention as telemetry)
if os.environ.get("MXNET_TPU_OBS_TRACE", "0") != "0":
    enable_tracing()
if os.environ.get("MXNET_TPU_OBS_GOODPUT", "0") != "0":
    enable_goodput()
_env_blackbox = os.environ.get("MXNET_TPU_OBS_BLACKBOX", "")
if _env_blackbox:
    flight.install(_env_blackbox)
_env_port = os.environ.get("MXNET_TPU_OBS_PORT", "")
if _env_port and _env_port != "0":
    server.serve(int(_env_port))
