"""``mx.image`` (reference: ``python/mxnet/image/image.py``): host-side
image IO and augmenters, PIL-backed (the reference uses OpenCV)."""
from .image import (CastAug, CenterCropAug, ColorJitterAug, HorizontalFlipAug,
                    ImageIter, RandomCropAug, ResizeAug, imdecode, imread,
                    imresize, CreateAugmenter)
