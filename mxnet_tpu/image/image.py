"""Image IO + legacy ImageIter (reference: ``python/mxnet/image/image.py``).

The reference decodes via OpenCV in C++ threads
(``iter_image_recordio_2.cc :: ImageRecordIOParser2``).  Here decode is
OpenCV-first too (PIL fallback) on the HOST in pure numpy -- no
per-image device round-trips -- and ``ImageIter`` fans the
decode+augment work over a thread pool (cv2 releases the GIL in the
codec), with ``PrefetchingIter`` overlapping the whole pipeline with
device compute.
"""
from __future__ import annotations

import io
import os

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array

try:
    import cv2 as _cv2
except ImportError:  # pragma: no cover - cv2 is in the image
    _cv2 = None

# magic bytes of the codecs imdecode handles
_IMG_SIGNATURES = (b"\xff\xd8\xff",            # JPEG
                   b"\x89PNG\r\n\x1a\n",       # PNG
                   b"BM",                        # BMP
                   b"GIF8",                      # GIF
                   b"RIFF")                      # WebP


def _looks_compressed(payload):
    return any(payload[:len(m)] == m for m in _IMG_SIGNATURES)


def _decode_np(buf, flag=1):
    """bytes -> HWC uint8 RGB (or L) numpy array, fastest available codec."""
    if _cv2 is not None:
        a = _cv2.imdecode(np.frombuffer(buf, np.uint8),
                          _cv2.IMREAD_COLOR if flag else
                          _cv2.IMREAD_GRAYSCALE)
        if a is not None:
            if flag:
                a = _cv2.cvtColor(a, _cv2.COLOR_BGR2RGB)
            else:
                a = a[:, :, None]
            return a
    from PIL import Image
    pil = Image.open(io.BytesIO(buf)).convert("RGB" if flag else "L")
    a = np.asarray(pil)
    if a.ndim == 2:
        a = a[:, :, None]
    return a


def _resize_np(a, w, h, interp=1):
    """HWC numpy resize on the host (no device round-trip)."""
    if _cv2 is not None:
        out = _cv2.resize(a, (w, h),
                          interpolation=_cv2.INTER_LINEAR if interp
                          else _cv2.INTER_NEAREST)
        if out.ndim == 2:
            out = out[:, :, None]
        return out
    from PIL import Image
    mode = Image.BILINEAR if interp else Image.NEAREST
    chans = []
    for c in range(a.shape[2]):
        chans.append(np.asarray(
            Image.fromarray(a[:, :, c]).resize((w, h), mode)))
    return np.stack(chans, axis=2)


def imread(filename, flag=1, to_rgb=True):
    """Read an image file to an HWC uint8 NDArray (reference: ``imread``)."""
    with open(filename, "rb") as f:
        return array(_decode_np(f.read(), flag))


def imdecode(buf, flag=1, to_rgb=True):
    """Decode a compressed image buffer (reference: ``imdecode``)."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    return array(_decode_np(bytes(buf), flag))


def imresize(src, w, h, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    if a.dtype == np.uint8:
        return array(_resize_np(a, w, h, interp))
    out = _resize_np(a.astype(np.float32), w, h, interp)
    return array(out)


def _as_np(src):
    return src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)


def _like(src, a):
    """Return ``a`` as the same container type as ``src`` (numpy stays
    numpy -- the ImageIter hot path never touches the device)."""
    return array(a) if isinstance(src, NDArray) else a


class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size

    def __call__(self, src):
        a = _as_np(src)
        h, w = a.shape[:2]
        if min(h, w) == self.size:
            return src
        if h > w:
            new_w, new_h = self.size, int(h * self.size / w)
        else:
            new_w, new_h = int(w * self.size / h), self.size
        return _like(src, _resize_np(a, new_w, new_h))


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, src):
        a = _as_np(src)
        w, h = self.size
        y0 = max((a.shape[0] - h) // 2, 0)
        x0 = max((a.shape[1] - w) // 2, 0)
        out = a[y0:y0 + h, x0:x0 + w]
        if out.shape[:2] != (h, w):
            out = _resize_np(out, w, h)
        return _like(src, out)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, src):
        a = _as_np(src)
        w, h = self.size
        y0 = np.random.randint(0, max(a.shape[0] - h, 0) + 1)
        x0 = np.random.randint(0, max(a.shape[1] - w, 0) + 1)
        out = a[y0:y0 + h, x0:x0 + w]
        if out.shape[:2] != (h, w):
            out = _resize_np(out, w, h)
        return _like(src, out)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return _like(src, np.ascontiguousarray(_as_np(src)[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        self.typ = typ

    def __call__(self, src):
        if isinstance(src, NDArray):
            return src.astype(self.typ)
        return np.asarray(src).astype(self.typ)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    def __call__(self, src):
        a = _as_np(src).astype(np.float32)
        if self.brightness:
            a *= 1.0 + np.random.uniform(-self.brightness, self.brightness)
        if self.contrast:
            f = 1.0 + np.random.uniform(-self.contrast, self.contrast)
            a = (a - a.mean()) * f + a.mean()
        if self.saturation:
            f = 1.0 + np.random.uniform(-self.saturation, self.saturation)
            gray = a.mean(axis=2, keepdims=True)
            a = gray + (a - gray) * f
        return _like(src, np.clip(a, 0, 255).astype(np.float32))


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference: ``CreateAugmenter``)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    auglist.append(CastAug())
    return auglist


class ImageIter:
    """Legacy image iterator over .rec or .lst (reference: ``ImageIter``).

    Yields ``DataBatch``-like objects with CHW float data; sharding via
    num_parts/part_index as the reference's distributed input contract.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", aug_list=None,
                 shuffle=False, num_parts=1, part_index=0, label_width=1,
                 preprocess_threads=4, dtype="float32", **kwargs):
        from ..recordio import MXIndexedRecordIO
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.shuffle = shuffle
        self.dtype = np.dtype(dtype)
        # an explicit CastAug in a user-supplied aug_list wins over the
        # dtype parameter; for the default list the dtype parameter wins
        # (and drops the redundant float32 CastAug)
        if aug_list is None:
            if self.dtype != np.float32:
                self.auglist = [a for a in self.auglist
                                if not isinstance(a, CastAug)]
            self._final_dtype = self.dtype
        else:
            self._final_dtype = None if any(
                isinstance(a, CastAug) for a in self.auglist)                 else self.dtype
        self._pool = None
        if preprocess_threads and preprocess_threads > 1:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(preprocess_threads)
        self._rec = None
        self._imglist = None
        if path_imgrec:
            idx_path = path_imgrec[:path_imgrec.rindex(".")] + ".idx"
            self._rec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
            keys = list(self._rec.keys)
        elif path_imglist:
            self._imglist = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    self._imglist.append(
                        (float(parts[1]), os.path.join(path_root, parts[-1])))
            keys = list(range(len(self._imglist)))
        else:
            raise MXNetError("need path_imgrec or path_imglist")
        # distributed sharding (reference: num_parts/part_index kwargs)
        self._keys = keys[part_index::num_parts]
        self.reset()

    def reset(self):
        self._order = np.random.permutation(len(self._keys)) if self.shuffle \
            else np.arange(len(self._keys))
        self._cursor = 0

    def close(self):
        """Release the record reader and the decode thread pool."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if self._rec is not None:
            self._rec.close()
            self._rec = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _process_record(self, rec):
        """One raw record (bytes) -> (CHW float array, label).  Pure
        host-side work: safe to fan out over the thread pool."""
        from ..recordio import unpack
        header, payload = unpack(rec)
        label = header.label
        c, h, w = self.data_shape
        payload = bytes(payload)
        if len(payload) == c * h * w:
            # raw (already-decoded) record: the im2rec --encoding .raw
            # fast path for hosts where codec throughput is the
            # bottleneck.  Raw records carry no shape metadata --
            # data_shape IS the contract.  A payload that length-matches
            # but starts with a codec signature is decoded instead; if
            # that decode fails (a raw image whose first pixels collide
            # with a 2-byte magic) it falls back to the raw reshape
            # rather than aborting the epoch.
            if not _looks_compressed(payload):
                img = np.frombuffer(payload, np.uint8).reshape(h, w, c)
                return self._augment(img), label
            try:
                img = _decode_np(payload, 1 if c == 3 else 0)
            except Exception:
                img = np.frombuffer(payload, np.uint8).reshape(h, w, c)
            return self._augment(img), label
        img = _decode_np(payload, 1 if c == 3 else 0)
        return self._augment(img), label

    def _process_file(self, key):
        label, path = self._imglist[self._keys[key]]
        with open(path, "rb") as f:
            img = _decode_np(f.read(), 1)
        return self._augment(img), label

    def _augment(self, img):
        for aug in self.auglist:
            img = aug(img)           # numpy in -> numpy out (host-side)
        a = _as_np(img)
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        if self._final_dtype is not None:
            a = a.astype(self._final_dtype, copy=False)
        return a

    def _read_one(self, key):
        if self._rec is not None:
            return self._process_record(self._rec.read_idx(self._keys[key]))
        return self._process_file(key)

    def __iter__(self):
        return self

    def next_np(self, out=None):
        """One batch as host numpy ``(data, labels, pad)`` -- the zero
        device-round-trip path the ImageRecordIter pipeline uses.

        ``out``: optional preallocated (batch, C, H, W) array filled in
        place (a reused staging buffer transfers much faster through the
        PJRT tunnel than fresh allocations)."""
        if self._cursor >= len(self._keys):
            raise StopIteration
        # final partial batch is padded by wrapping to the start
        # (reference behavior: batch.pad records the overhang)
        pad = max(0, self._cursor + self.batch_size - len(self._keys))
        idxs = [self._order[(self._cursor + i) % len(self._keys)]
                for i in range(self.batch_size)]
        if self._rec is not None:
            # one thread-pooled native batch read of the record bytes
            # (the shared reader handle is NOT safe for concurrent
            # read_idx), then parallel decode+augment over the buffers
            recs = self._rec.read_batch([self._keys[k] for k in idxs])
            if self._pool is not None:
                results = list(self._pool.map(self._process_record, recs))
            else:
                results = [self._process_record(r) for r in recs]
        elif self._pool is not None:
            results = list(self._pool.map(self._process_file, idxs))
        else:
            results = [self._process_file(i) for i in idxs]
        datas = [a for a, _ in results]
        labels = [np.atleast_1d(np.asarray(l, np.float32))[0]
                  for _, l in results]
        self._cursor += self.batch_size
        if out is not None:
            for i, a in enumerate(datas):
                out[i] = a
            return out, np.asarray(labels), pad
        return np.stack(datas), np.asarray(labels), pad

    def __next__(self):
        data, labels, pad = self.next_np()
        from ..io import DataBatch
        return DataBatch(data=[array(data)], label=[array(labels)],
                         pad=pad)

    next = __next__
