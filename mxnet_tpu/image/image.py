"""Image IO + legacy ImageIter (reference: ``python/mxnet/image/image.py``).

The reference decodes via OpenCV in C++ threads; here PIL does host-side
decode (GIL released in the codec), and the DataLoader/iterator layer
provides the threading.
"""
from __future__ import annotations

import io
import os

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array


def imread(filename, flag=1, to_rgb=True):
    """Read an image file to an HWC uint8 NDArray (reference: ``imread``)."""
    from PIL import Image
    pil = Image.open(filename)
    pil = pil.convert("RGB" if flag else "L")
    arr = np.asarray(pil)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return array(arr)


def imdecode(buf, flag=1, to_rgb=True):
    """Decode a compressed image buffer (reference: ``imdecode``)."""
    from PIL import Image
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    pil = Image.open(io.BytesIO(bytes(buf)))
    pil = pil.convert("RGB" if flag else "L")
    arr = np.asarray(pil)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return array(arr)


def imresize(src, w, h, interp=1):
    import jax
    import jax.numpy as jnp
    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = jax.image.resize(jnp.asarray(a, jnp.float32), (h, w, a.shape[2]),
                           "bilinear" if interp else "nearest")
    if a.dtype == np.uint8:
        out = jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)
    return NDArray(out)


class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size

    def __call__(self, src):
        a = src.asnumpy()
        h, w = a.shape[:2]
        if min(h, w) == self.size:
            return src
        if h > w:
            new_w, new_h = self.size, int(h * self.size / w)
        else:
            new_w, new_h = int(w * self.size / h), self.size
        return imresize(src, new_w, new_h)


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, src):
        a = src.asnumpy()
        w, h = self.size
        y0 = max((a.shape[0] - h) // 2, 0)
        x0 = max((a.shape[1] - w) // 2, 0)
        out = a[y0:y0 + h, x0:x0 + w]
        if out.shape[:2] != (h, w):
            return imresize(array(out), w, h)
        return array(out)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, src):
        a = src.asnumpy()
        w, h = self.size
        y0 = np.random.randint(0, max(a.shape[0] - h, 0) + 1)
        x0 = np.random.randint(0, max(a.shape[1] - w, 0) + 1)
        out = a[y0:y0 + h, x0:x0 + w]
        if out.shape[:2] != (h, w):
            return imresize(array(out), w, h)
        return array(out)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return array(np.ascontiguousarray(src.asnumpy()[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    def __call__(self, src):
        a = src.asnumpy().astype(np.float32)
        if self.brightness:
            a *= 1.0 + np.random.uniform(-self.brightness, self.brightness)
        if self.contrast:
            f = 1.0 + np.random.uniform(-self.contrast, self.contrast)
            a = (a - a.mean()) * f + a.mean()
        if self.saturation:
            f = 1.0 + np.random.uniform(-self.saturation, self.saturation)
            gray = a.mean(axis=2, keepdims=True)
            a = gray + (a - gray) * f
        return array(np.clip(a, 0, 255))


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference: ``CreateAugmenter``)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    auglist.append(CastAug())
    return auglist


class ImageIter:
    """Legacy image iterator over .rec or .lst (reference: ``ImageIter``).

    Yields ``DataBatch``-like objects with CHW float data; sharding via
    num_parts/part_index as the reference's distributed input contract.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", aug_list=None,
                 shuffle=False, num_parts=1, part_index=0, label_width=1,
                 **kwargs):
        from ..recordio import MXIndexedRecordIO
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.shuffle = shuffle
        self._rec = None
        self._imglist = None
        if path_imgrec:
            idx_path = path_imgrec[:path_imgrec.rindex(".")] + ".idx"
            self._rec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
            keys = list(self._rec.keys)
        elif path_imglist:
            self._imglist = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    self._imglist.append(
                        (float(parts[1]), os.path.join(path_root, parts[-1])))
            keys = list(range(len(self._imglist)))
        else:
            raise MXNetError("need path_imgrec or path_imglist")
        # distributed sharding (reference: num_parts/part_index kwargs)
        self._keys = keys[part_index::num_parts]
        self.reset()

    def reset(self):
        self._order = np.random.permutation(len(self._keys)) if self.shuffle \
            else np.arange(len(self._keys))
        self._cursor = 0

    def _read_one(self, key):
        from ..recordio import unpack_img
        if self._rec is not None:
            header, img = unpack_img(self._rec.read_idx(self._keys[key]))
            label = header.label
            img = array(img)
        else:
            label, path = self._imglist[self._keys[key]]
            img = imread(path)
        for aug in self.auglist:
            img = aug(img)
        a = img.asnumpy()
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        return a, label

    def __iter__(self):
        return self

    def __next__(self):
        if self._cursor >= len(self._keys):
            raise StopIteration
        # final partial batch is padded by wrapping to the start
        # (reference behavior: batch.pad records the overhang)
        pad = max(0, self._cursor + self.batch_size - len(self._keys))
        datas, labels = [], []
        for i in range(self.batch_size):
            pos = (self._cursor + i) % len(self._keys)
            a, l = self._read_one(self._order[pos])
            datas.append(a)
            labels.append(np.atleast_1d(np.asarray(l, np.float32))[0])
        self._cursor += self.batch_size
        from ..io import DataBatch
        return DataBatch(data=[array(np.stack(datas))],
                         label=[array(np.asarray(labels))], pad=pad)

    next = __next__
