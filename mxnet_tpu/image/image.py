"""Image IO + legacy ImageIter (reference: ``python/mxnet/image/image.py``).

The reference decodes via OpenCV in C++ threads
(``iter_image_recordio_2.cc :: ImageRecordIOParser2``).  Here decode is
OpenCV-first too (PIL fallback) on the HOST in pure numpy -- no
per-image device round-trips -- and ``ImageIter`` fans the
decode+augment work over a thread pool (cv2 releases the GIL in the
codec), with ``PrefetchingIter`` overlapping the whole pipeline with
device compute.
"""
from __future__ import annotations

import io
import os

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array

try:
    import cv2 as _cv2
except ImportError:  # pragma: no cover - cv2 is in the image
    _cv2 = None

# magic bytes of the codecs imdecode handles
_IMG_SIGNATURES = (b"\xff\xd8\xff",            # JPEG
                   b"\x89PNG\r\n\x1a\n",       # PNG
                   b"BM",                        # BMP
                   b"GIF8",                      # GIF
                   b"RIFF")                      # WebP


def _looks_compressed(payload):
    return any(payload[:len(m)] == m for m in _IMG_SIGNATURES)


def _decode_np(buf, flag=1):
    """bytes -> HWC uint8 RGB (or L) numpy array, fastest available codec."""
    if _cv2 is not None:
        a = _cv2.imdecode(np.frombuffer(buf, np.uint8),
                          _cv2.IMREAD_COLOR if flag else
                          _cv2.IMREAD_GRAYSCALE)
        if a is not None:
            if flag:
                # BGR -> RGB as a zero-copy stride flip: the later
                # transpose+cast pass materializes it, saving cvtColor's
                # full-image pass
                a = a[:, :, ::-1]
            else:
                a = a[:, :, None]
            return a
    from PIL import Image
    pil = Image.open(io.BytesIO(buf)).convert("RGB" if flag else "L")
    a = np.asarray(pil)
    if a.ndim == 2:
        a = a[:, :, None]
    return a


def _resize_np(a, w, h, interp=1):
    """HWC numpy resize on the host (no device round-trip)."""
    if _cv2 is not None:
        out = _cv2.resize(a, (w, h),
                          interpolation=_cv2.INTER_LINEAR if interp
                          else _cv2.INTER_NEAREST)
        if out.ndim == 2:
            out = out[:, :, None]
        return out
    from PIL import Image
    mode = Image.BILINEAR if interp else Image.NEAREST
    chans = []
    for c in range(a.shape[2]):
        chans.append(np.asarray(
            Image.fromarray(a[:, :, c]).resize((w, h), mode)))
    return np.stack(chans, axis=2)


def imread(filename, flag=1, to_rgb=True):
    """Read an image file to an HWC uint8 NDArray (reference: ``imread``)."""
    with open(filename, "rb") as f:
        return array(_decode_np(f.read(), flag))


def imdecode(buf, flag=1, to_rgb=True):
    """Decode a compressed image buffer (reference: ``imdecode``)."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    return array(_decode_np(bytes(buf), flag))


def imresize(src, w, h, interp=1):
    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    if a.dtype == np.uint8:
        return array(_resize_np(a, w, h, interp))
    out = _resize_np(a.astype(np.float32), w, h, interp)
    return array(out)


def _as_np(src):
    return src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)


def _like(src, a):
    """Return ``a`` as the same container type as ``src`` (numpy stays
    numpy -- the ImageIter hot path never touches the device)."""
    return array(a) if isinstance(src, NDArray) else a


class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size

    def __call__(self, src):
        a = _as_np(src)
        h, w = a.shape[:2]
        if min(h, w) == self.size:
            return src
        if h > w:
            new_w, new_h = self.size, int(h * self.size / w)
        else:
            new_w, new_h = int(w * self.size / h), self.size
        return _like(src, _resize_np(a, new_w, new_h))


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, src):
        a = _as_np(src)
        w, h = self.size
        y0 = max((a.shape[0] - h) // 2, 0)
        x0 = max((a.shape[1] - w) // 2, 0)
        out = a[y0:y0 + h, x0:x0 + w]
        if out.shape[:2] != (h, w):
            out = _resize_np(out, w, h)
        return _like(src, out)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, src):
        a = _as_np(src)
        w, h = self.size
        y0 = np.random.randint(0, max(a.shape[0] - h, 0) + 1)
        x0 = np.random.randint(0, max(a.shape[1] - w, 0) + 1)
        out = a[y0:y0 + h, x0:x0 + w]
        if out.shape[:2] != (h, w):
            out = _resize_np(out, w, h)
        return _like(src, out)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return _like(src, np.ascontiguousarray(_as_np(src)[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        self.typ = typ

    def __call__(self, src):
        if isinstance(src, NDArray):
            return src.astype(self.typ)
        return np.asarray(src).astype(self.typ)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    def __call__(self, src):
        a = _as_np(src).astype(np.float32)
        if self.brightness:
            a *= 1.0 + np.random.uniform(-self.brightness, self.brightness)
        if self.contrast:
            f = 1.0 + np.random.uniform(-self.contrast, self.contrast)
            a = (a - a.mean()) * f + a.mean()
        if self.saturation:
            f = 1.0 + np.random.uniform(-self.saturation, self.saturation)
            gray = a.mean(axis=2, keepdims=True)
            a = gray + (a - gray) * f
        return _like(src, np.clip(a, 0, 255).astype(np.float32))


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference: ``CreateAugmenter``)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    auglist.append(CastAug())
    return auglist


def _process_record_np(rec, data_shape, auglist, final_dtype, dst=None):
    """One raw record (bytes) -> (CHW array, label): standalone so both
    the thread pool and the process pool can run it.  With ``dst`` the
    result is written (cast fused with the copy -- one memory pass)
    into the given CHW buffer row and ``dst`` is returned."""
    from ..recordio import _unpack_view
    header, payload = _unpack_view(rec)   # zero-copy payload view
    label = header.label
    c, h, w = data_shape
    img = None
    if len(payload) == c * h * w:
        # raw (already-decoded) record: the im2rec --encoding .raw fast
        # path.  Raw records carry no shape metadata -- data_shape IS
        # the contract.  A payload that length-matches but starts with a
        # codec signature is decoded instead; if that decode fails (raw
        # pixels colliding with a 2-byte magic) it falls back to the
        # raw reshape rather than aborting the epoch.
        if not _looks_compressed(payload):
            img = np.frombuffer(payload, np.uint8).reshape(h, w, c)
        else:
            try:
                img = _decode_np(payload, 1 if c == 3 else 0)
            except Exception:
                img = np.frombuffer(payload, np.uint8).reshape(h, w, c)
    else:
        img = _decode_np(payload, 1 if c == 3 else 0)
    for aug in auglist:
        img = aug(img)               # numpy in -> numpy out (host-side)
    a = _as_np(img)
    if a.ndim == 3:
        a = a.transpose(2, 0, 1)
    if dst is not None:
        np.copyto(dst, a, casting="unsafe")
        return dst, label
    if final_dtype is not None:
        a = a.astype(final_dtype, copy=False)
    return a, label


# -- process-pool decode workers (reference: ImageRecordIOParser2's
# C++ decode threads; here real processes so numpy augmenters scale
# past the GIL, with a SharedMemory output slab as the cpu_shared
# handoff) --------------------------------------------------------------

_POOL_STATE = {}


def _pool_worker_init(idx_path, rec_path, shm_name, slab_shape, slab_dtype,
                      auglist, data_shape, final_dtype):
    from multiprocessing import shared_memory
    from ..recordio import MXIndexedRecordIO
    np.random.seed((os.getpid() * 2654435761) % (2 ** 31))
    shm = shared_memory.SharedMemory(name=shm_name)
    _POOL_STATE["shm"] = shm
    _POOL_STATE["slab"] = np.ndarray(slab_shape, dtype=slab_dtype,
                                     buffer=shm.buf)
    _POOL_STATE["rec"] = MXIndexedRecordIO(idx_path, rec_path, "r")
    _POOL_STATE["args"] = (data_shape, auglist, final_dtype)


def _pool_process_chunk(task):
    offs, keys = task
    data_shape, auglist, final_dtype = _POOL_STATE["args"]
    rec = _POOL_STATE["rec"]
    slab = _POOL_STATE["slab"]
    labels = []
    for o, k in zip(offs, keys):
        _, label = _process_record_np(rec.read_idx(k), data_shape,
                                      auglist, final_dtype, dst=slab[o])
        labels.append(float(np.atleast_1d(np.asarray(label))[0]))
    return offs, labels


class ImageIter:
    """Legacy image iterator over .rec or .lst (reference: ``ImageIter``).

    Yields ``DataBatch``-like objects with CHW float data; sharding via
    num_parts/part_index as the reference's distributed input contract.

    ``preprocess_threads`` fans decode+augment over threads (cv2
    releases the GIL in the codec); ``preprocess_procs`` > 0 instead
    uses a forkserver-based PROCESS pool with a SharedMemory output
    slab -- the numpy augmenters scale past the GIL, the decoded batch
    crosses processes without pickling (the reference's cpu_shared
    storage analog, ``cpu_shared_storage_manager.h``).
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", aug_list=None,
                 shuffle=False, num_parts=1, part_index=0, label_width=1,
                 preprocess_threads=4, preprocess_procs=0,
                 dtype="float32", **kwargs):
        from ..recordio import MXIndexedRecordIO
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.shuffle = shuffle
        self.dtype = np.dtype(dtype)
        # an explicit CastAug in a user-supplied aug_list wins over the
        # dtype parameter; for the default list the dtype parameter wins
        # and the CastAug is dropped entirely -- the cast happens fused
        # with the copy into the batch buffer (one memory pass, not two)
        if aug_list is None:
            self.auglist = [a for a in self.auglist
                            if not isinstance(a, CastAug)]
            self._final_dtype = self.dtype
        else:
            self._final_dtype = None if any(
                isinstance(a, CastAug) for a in self.auglist)                 else self.dtype
        # dtype of the assembled batch buffer
        self._batch_dtype = self._final_dtype
        if self._batch_dtype is None:
            self._batch_dtype = np.dtype("float32")
            for a in self.auglist:
                if isinstance(a, CastAug):
                    self._batch_dtype = np.dtype(a.typ)
        self._pool = None
        self._proc_pool = None
        self._shm = None
        self._main_file_restore = None
        self._n_procs = int(preprocess_procs or 0)
        if self._n_procs == 0 and preprocess_threads and \
                preprocess_threads > 1:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(preprocess_threads)
        self._rec = None
        self._imglist = None
        if path_imgrec:
            idx_path = path_imgrec[:path_imgrec.rindex(".")] + ".idx"
            self._rec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
            keys = list(self._rec.keys)
        elif path_imglist:
            self._imglist = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    self._imglist.append(
                        (float(parts[1]), os.path.join(path_root, parts[-1])))
            keys = list(range(len(self._imglist)))
        else:
            raise MXNetError("need path_imgrec or path_imglist")
        # distributed sharding (reference: num_parts/part_index kwargs)
        self._keys = keys[part_index::num_parts]
        if self._n_procs > 0:
            if self._rec is None:
                raise MXNetError(
                    "preprocess_procs needs path_imgrec (each worker "
                    "process opens its own record reader)")
            self._start_proc_pool(path_imgrec)
        self.reset()

    def _start_proc_pool(self, path_imgrec):
        import multiprocessing as mp
        from multiprocessing import shared_memory
        slab_dtype = self._batch_dtype
        slab_shape = (self.batch_size,) + self.data_shape
        self._slab_dtype = slab_dtype
        self._shm = shared_memory.SharedMemory(
            create=True,
            size=int(np.prod(slab_shape)) * slab_dtype.itemsize)
        self._slab = np.ndarray(slab_shape, dtype=slab_dtype,
                                buffer=self._shm.buf)
        idx_path = path_imgrec[:path_imgrec.rindex(".")] + ".idx"
        # forkserver: workers fork from a CLEAN server process (itself
        # launched by exec), never from this process -- forking a
        # JAX-multithreaded process is deadlock-prone (os.fork
        # RuntimeWarning; reference took the same hazard seriously in
        # initialize.cc :: LibraryInitializer's fork handlers).  The
        # initargs (augmenter list included) travel by pickle, which
        # they support.
        try:
            ctx = mp.get_context("forkserver")
        except ValueError:  # pragma: no cover - non-POSIX fallback
            ctx = mp.get_context("spawn")
        # forkserver/spawn workers re-execute __main__ when it has a
        # __file__; a parent launched from stdin or a notebook cell has
        # the bogus path '<stdin>', which makes every worker crash on
        # import and the pool respawn forever (a hang, not an error).
        # The workers only need _pool_worker_init from THIS importable
        # module, so drop the unloadable __file__ for the POOL'S
        # LIFETIME -- the Pool's maintenance thread respawns dead
        # workers later, so the attr must stay gone while the pool
        # lives -- and restore it in close() once terminate()+join()
        # make respawns impossible.  Mutating __main__ forever was a
        # process-global side effect other tooling could observe
        # (ADVICE round-5 low).
        import sys as _sys
        main_mod = _sys.modules.get("__main__")
        main_file = getattr(main_mod, "__file__", None)
        if main_file is not None and not os.path.exists(main_file):
            del main_mod.__file__
            self._main_file_restore = (main_mod, main_file)
        self._proc_pool = ctx.Pool(
            self._n_procs, initializer=_pool_worker_init,
            initargs=(idx_path, path_imgrec, self._shm.name,
                      slab_shape, slab_dtype, self.auglist,
                      self.data_shape, self._final_dtype))

    def reset(self):
        self._order = np.random.permutation(len(self._keys)) if self.shuffle \
            else np.arange(len(self._keys))
        self._cursor = 0

    def close(self):
        """Release the record reader, decode pools, and shared slab."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if self._proc_pool is not None:
            self._proc_pool.terminate()
            self._proc_pool.join()
            self._proc_pool = None
        if self._main_file_restore is not None:
            # the pool is dead (terminate+join above): no maintenance
            # thread can respawn a worker, so the spawn workaround ends
            # here and __main__ goes back exactly as found
            mod, path = self._main_file_restore
            if not hasattr(mod, "__file__"):
                mod.__file__ = path
            self._main_file_restore = None
        if self._shm is not None:
            self._slab = None
            try:
                self._shm.close()
                self._shm.unlink()
            except (FileNotFoundError, OSError):
                pass
            self._shm = None
        if self._rec is not None:
            self._rec.close()
            self._rec = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _process_record(self, rec):
        """One raw record (bytes) -> (CHW float array, label).  Pure
        host-side work: safe to fan out over the thread pool."""
        return _process_record_np(rec, self.data_shape, self.auglist,
                                  self._final_dtype)

    def _process_file(self, key):
        label, path = self._imglist[self._keys[key]]
        with open(path, "rb") as f:
            img = _decode_np(f.read(), 1)
        return self._augment(img), label

    def _augment(self, img):
        for aug in self.auglist:
            img = aug(img)           # numpy in -> numpy out (host-side)
        a = _as_np(img)
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        if self._final_dtype is not None:
            a = a.astype(self._final_dtype, copy=False)
        return a

    def _read_one(self, key):
        if self._rec is not None:
            return self._process_record(self._rec.read_idx(self._keys[key]))
        return self._process_file(key)

    def __iter__(self):
        return self

    def next_np(self, out=None):
        """One batch as host numpy ``(data, labels, pad)`` -- the zero
        device-round-trip path the ImageRecordIter pipeline uses.

        ``out``: optional preallocated (batch, C, H, W) array filled in
        place (a reused staging buffer transfers much faster through the
        PJRT tunnel than fresh allocations)."""
        if self._cursor >= len(self._keys):
            raise StopIteration
        # final partial batch is padded by wrapping to the start
        # (reference behavior: batch.pad records the overhang)
        pad = max(0, self._cursor + self.batch_size - len(self._keys))
        idxs = [self._order[(self._cursor + i) % len(self._keys)]
                for i in range(self.batch_size)]
        if self._proc_pool is not None:
            # process-pool mode: each worker reads its keys from its own
            # reader and writes decoded images straight into the shared
            # slab -- no record or image bytes cross a process boundary
            keys = [self._keys[k] for k in idxs]
            nchunks = min(self._n_procs, len(keys))
            tasks = []
            for ci in range(nchunks):
                offs = list(range(ci, len(keys), nchunks))
                tasks.append((offs, [keys[o] for o in offs]))
            labels = np.empty(self.batch_size, np.float32)
            for offs, ls in self._proc_pool.map(_pool_process_chunk,
                                                tasks):
                for o, l in zip(offs, ls):
                    labels[o] = l
            self._cursor += self.batch_size
            if out is not None:
                np.copyto(out, self._slab)
                return out, labels, pad
            return self._slab.copy(), labels, pad
        # decode+augment writes straight into the batch buffer (cast
        # fused with the copy) -- no per-image float temporaries, no
        # np.stack pass
        buf = out if out is not None else np.empty(
            (self.batch_size,) + self.data_shape, self._batch_dtype)
        if self._rec is not None:
            # one thread-pooled native batch read of the record bytes
            # (the shared reader handle is NOT safe for concurrent
            # read_idx), then parallel decode+augment over the buffers
            recs = self._rec.read_batch([self._keys[k] for k in idxs])

            def fill_rec(i):
                _, label = _process_record_np(
                    recs[i], self.data_shape, self.auglist,
                    self._final_dtype, dst=buf[i])
                return label
            if self._pool is not None:
                results = list(self._pool.map(fill_rec,
                                              range(len(recs))))
            else:
                results = [fill_rec(i) for i in range(len(recs))]
        else:
            def fill_file(args):
                i, key = args
                a, label = self._process_file(key)
                np.copyto(buf[i], a, casting="unsafe")
                return label
            if self._pool is not None:
                results = list(self._pool.map(fill_file,
                                              enumerate(idxs)))
            else:
                results = [fill_file(x) for x in enumerate(idxs)]
        labels = np.asarray(
            [np.atleast_1d(np.asarray(l, np.float32))[0]
             for l in results], np.float32)
        self._cursor += self.batch_size
        return buf, labels, pad

    def __next__(self):
        data, labels, pad = self.next_np()
        from ..io import DataBatch
        return DataBatch(data=[array(data)], label=[array(labels)],
                         pad=pad)

    next = __next__

    def device_feed(self, ctx=None, mesh=None, sharding=None,
                    transform=None, depth=None, compact=None):
        """Wrap this iterator in a :class:`mxnet_tpu.dataio.DeviceFeed`:
        decoded batches leave ``next_np`` as host numpy (in this iter's
        dtype -- construct with ``dtype='uint8'`` for compact staging)
        and a background thread overlaps the async host->device transfer
        with the consumer's compute (docs/data_pipeline.md)."""
        from ..dataio import DeviceFeed
        return DeviceFeed(self, ctx=ctx, mesh=mesh, sharding=sharding,
                          transform=transform, depth=depth,
                          compact=compact)
