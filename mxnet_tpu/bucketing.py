"""Dtype-group flatten/concat bucketing.

PR 9 taught the host collectives to coalesce a whole tensor list into
ONE contiguous buffer per dtype (``distributed.host_allreduce_bucketed``)
instead of one RPC per tensor.  The fused bucket-flattened optimizer
update (``mxnet_tpu.kernels.optimizer_update``) needs the exact same
grouping over *traced* jax arrays, so the machinery lives here once and
both consumers share it: group by dtype preserving input order, flatten
each group into one 1-D buffer, split results back to the original
shapes.

The helpers are array-module agnostic: pass ``xp=numpy`` for host
buffers (collectives) or ``xp=jax.numpy`` for traced buffers (the
compiled optimizer update).
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

__all__ = ["dtype_groups", "flatten_group", "split_group"]


def dtype_groups(arrays: Sequence[Any]) -> List[Tuple[Any, List[int]]]:
    """Group ``arrays`` by dtype, preserving first-seen order.

    Returns ``[(dtype, [index, ...]), ...]`` where indices point into the
    input sequence in their original order -- the contract both the host
    collectives and the fused optimizer rely on to reassemble results.
    """
    order: List[Any] = []
    groups: Dict[Any, List[int]] = {}
    for i, a in enumerate(arrays):
        dt = a.dtype
        if dt not in groups:
            groups[dt] = []
            order.append(dt)
        groups[dt].append(i)
    return [(dt, groups[dt]) for dt in order]


def flatten_group(arrays: Sequence[Any], idxs: Sequence[int], xp) -> Any:
    """One contiguous 1-D buffer holding ``arrays[i].ravel()`` for every
    ``i`` in ``idxs``, concatenated in order.  A single-element group
    skips the concat (it would copy)."""
    flat = [arrays[i].ravel() for i in idxs]
    return xp.concatenate(flat) if len(flat) > 1 else flat[0]


def split_group(buf: Any, shapes: Sequence[Tuple[int, ...]]) -> List[Any]:
    """Split a flat buffer produced by :func:`flatten_group` back into
    pieces of the given ``shapes`` (works on numpy and jax arrays --
    basic slicing + reshape only)."""
    out = []
    off = 0
    for shape in shapes:
        n = 1
        for d in shape:
            n *= int(d)
        out.append(buf[off:off + n].reshape(shape))
        off += n
    return out
