"""Device contexts: cpu / tpu (gpu maps to the accelerator if present).

TPU-native re-design of the reference's ``python/mxnet/context.py ::
Context, cpu(), gpu(), current_context()`` and ``include/mxnet/base.h ::
Context``.  A Context names a JAX device; NDArrays are placed on it with
``jax.device_put`` and ops run where their inputs live (XLA's async runtime
replaces the reference's per-device engine worker threads).
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus", "gpu_memory_info", "DeviceType"]


class DeviceType:
    kCPU = 1
    kGPU = 2  # alias for the accelerator in this build
    kTPU = 2
    kCPUPinned = 3
    kCPUShared = 5


_DEVTYPE_NAMES = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
_DEVTYPE_IDS = {"cpu": 1, "gpu": 2, "tpu": 2, "cpu_pinned": 3, "cpu_shared": 5}


def _accelerator_platforms():
    # Platforms that count as the "gpu/tpu" device type, in preference order.
    return ("tpu", "axon", "gpu", "cuda", "rocm")


def _jax_devices_for(dev_type_name):
    # Addressable devices only: in a multi-process world jax.devices()
    # spans every host, and placing data on another process's device is
    # an error (contexts are per-worker, like the reference).  Real
    # backend-initialization failures propagate with their root cause;
    # only "this platform is absent" is treated as empty.
    local = jax.local_devices()
    if dev_type_name == "cpu":
        cpus = [d for d in local if d.platform == "cpu"]
        if not cpus:
            try:
                cpus = jax.local_devices(backend="cpu")
            except RuntimeError:
                cpus = []
        return cpus
    for plat in _accelerator_platforms():
        devs = [d for d in local if d.platform == plat]
        if devs:
            return devs
    return []


class Context:
    """A device context (reference: ``context.py :: Context``).

    Supports the reference's thread-local ``with ctx:`` stack.  ``tpu`` is
    the first-class accelerator type per the north star; ``gpu`` is accepted
    as an alias so reference scripts run unchanged.
    """

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in _DEVTYPE_IDS:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_typeid = _DEVTYPE_IDS[device_type]
            self.device_id = device_id

    @property
    def device_type(self):
        return _DEVTYPE_NAMES[self.device_typeid]

    def __eq__(self, other):
        return isinstance(other, Context) and \
            self.device_typeid == other.device_typeid and \
            self.device_id == other.device_id

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __str__ = __repr__

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *args):
        Context._default_ctx.stack.pop()

    # -- JAX mapping ---------------------------------------------------
    def jax_device(self):
        """The jax.Device this context names (raises if absent)."""
        name = "cpu" if self.device_typeid in (1, 3, 5) else "tpu"
        devs = _jax_devices_for(name)
        if not devs:
            raise MXNetError("no %s device available" % name)
        if self.device_id >= len(devs):
            raise MXNetError("%s(%d) out of range: %d device(s) present"
                             % (name, self.device_id, len(devs)))
        return devs[self.device_id]

    def empty_cache(self):
        """Reference: ``Context.empty_cache`` -- XLA manages HBM; no-op."""

    def memory_info(self):
        """(bytes_in_use, bytes_limit) for this device (reference:
        ``mx.context.gpu_memory_info``).  PJRT owns the allocator; this
        is its accounting surface.  Returns (0, 0) when the backend does
        not expose stats (e.g. a tunneled device)."""
        try:
            stats = self.jax_device().memory_stats()
        except Exception:
            stats = None
        if not stats:
            return (0, 0)
        return (int(stats.get("bytes_in_use", 0)),
                int(stats.get("bytes_limit",
                              stats.get("bytes_reservable_limit", 0))))


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Accelerator context; alias of :func:`tpu` in this build."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """First-class TPU context (the north star's ``mx.tpu()``)."""
    return Context("tpu", device_id)


def num_gpus():
    return len(_jax_devices_for("tpu"))


def num_tpus():
    return len(_jax_devices_for("tpu"))


def current_context():
    """Reference: ``context.py :: current_context`` (thread-local stack)."""
    stack = getattr(Context._default_ctx, "stack", None)
    if stack:
        return stack[-1]
    return Context("cpu", 0)


def gpu_memory_info(device_id=0):
    """(free, total) bytes on the accelerator (reference:
    ``mx.context.gpu_memory_info``; here the TPU's HBM accounting).
    (0, 0) when the backend reports no usable limit."""
    used, limit = tpu(device_id).memory_info()
    if limit <= 0:
        return (0, 0)
    return (max(limit - used, 0), limit)
