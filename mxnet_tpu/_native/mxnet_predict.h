/* C predict API (reference: include/mxnet/c_predict_api.h).
 *
 * Self-contained edge inference over exported ONNX artifacts
 * (mx.onnx.export_model): no Python, no protobuf, no BLAS.  Build the
 * runtime with:
 *
 *   g++ -O2 -shared -fPIC -std=c++17 predict_native.cc -o libmxtpu_predict.so
 *
 * and link this header's functions against it.  All tensors are float32;
 * shapes are int64.  Functions return 0 on success, -1 on failure with
 * the message available from MXPredGetLastError().
 */
#ifndef MXNET_TPU_PREDICT_H_
#define MXNET_TPU_PREDICT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* PredictorHandle;

const char* MXPredGetLastError(void);

/* Create a predictor from in-memory ONNX bytes / an .onnx file. */
int MXPredCreate(const char* model_bytes, int64_t model_len,
                 PredictorHandle* out);
int MXPredCreateFromFile(const char* path, PredictorHandle* out);

/* Bind an input by name (NULL or "" = the graph's first input). */
int MXPredSetInput(PredictorHandle h, const char* name, const float* data,
                   const int64_t* shape, int ndim);

int MXPredForward(PredictorHandle h);

/* Query output `index`: shape first (shape may be NULL to get ndim),
 * then the data. */
int MXPredGetOutputShape(PredictorHandle h, int index, int64_t* shape,
                         int* ndim);
int MXPredGetOutput(PredictorHandle h, int index, float* out, int64_t size);

void MXPredFree(PredictorHandle h);

/* .params parameter-container reader (reference: c_predict_api.h ::
 * MXNDListCreate/MXNDListGet/MXNDListFree).  Loads the framework's
 * .params files with no Python in the loop; stored dtypes (fp32/fp64/
 * fp16/bf16/int8..int64/uint8) are exposed as float, as upstream does.
 * Pointers returned by MXNDListGet stay valid until MXNDListFree. */
typedef void* NDListHandle;
int MXNDListCreate(const char* nd_file_bytes, int64_t nd_file_size,
                   NDListHandle* out, int64_t* out_length);
int MXNDListCreateFromFile(const char* path, NDListHandle* out,
                           int64_t* out_length);
int MXNDListGet(NDListHandle h, int64_t index, const char** out_key,
                const float** out_data, const int64_t** out_shape,
                int* out_ndim);
void MXNDListFree(NDListHandle h);

#ifdef __cplusplus
}
#endif

#endif /* MXNET_TPU_PREDICT_H_ */
