// Native RecordIO engine (TPU-framework runtime component).
//
// The data path between disk and the TPU host buffer is CPU-bound Python
// in the fallback implementation; this C++ engine provides the same
// dmlc-style framing
//
//     [kMagic u32][(cflag<<29)|length u32][payload][pad to 4B]
//
// (cflag: 0 whole, 1 first, 2 middle, 3 last chunk) with buffered
// sequential IO and a thread-pooled batched random-access reader used by
// the ImageRecordIter prefetch pipeline.  Reference analogs:
// dmlc-core recordio.h framing; src/io/iter_image_recordio_2.cc's
// multi-threaded record loader.  Re-implemented from the published
// format specification, not translated code.
//
// C ABI only (consumed via ctypes -- no pybind11 in the image).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <atomic>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xCED7230A;
constexpr uint32_t kMaxChunk = (1u << 29) - 1;
constexpr size_t kBufSize = 1u << 20;  // 1 MiB stdio buffer

struct Rio {
  FILE* f = nullptr;
  bool writable = false;
  std::vector<char> iobuf;
};

// Read one framed record (reassembling chunks) from f at its current
// position.  Returns malloc'd buffer in *out and its length, -1 on
// clean EOF, -2 on corruption.
long read_record(FILE* f, char** out) {
  std::string data;
  for (;;) {
    uint32_t hdr[2];
    size_t got = fread(hdr, 1, sizeof(hdr), f);
    if (got < sizeof(hdr)) {
      if (data.empty() && got == 0) return -1;  // clean EOF
      return -2;                                // truncated
    }
    if (hdr[0] != kMagic) return -2;
    uint32_t cflag = hdr[1] >> 29;
    uint32_t length = hdr[1] & kMaxChunk;
    size_t old = data.size();
    data.resize(old + length);
    if (length && fread(&data[old], 1, length, f) != length) return -2;
    uint32_t pad = (4 - length % 4) % 4;
    if (pad && fseek(f, pad, SEEK_CUR) != 0) return -2;
    if (cflag == 0 || cflag == 3) break;
  }
  char* buf = static_cast<char*>(malloc(data.size() ? data.size() : 1));
  if (!buf) return -2;
  memcpy(buf, data.data(), data.size());
  *out = buf;
  return static_cast<long>(data.size());
}

int write_chunk(FILE* f, uint32_t cflag, const char* buf, uint32_t len) {
  uint32_t hdr[2] = {kMagic, (cflag << 29) | len};
  if (fwrite(hdr, 1, sizeof(hdr), f) != sizeof(hdr)) return -1;
  if (len && fwrite(buf, 1, len, f) != len) return -1;
  uint32_t pad = (4 - len % 4) % 4;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && fwrite(zeros, 1, pad, f) != pad) return -1;
  return 0;
}

}  // namespace

extern "C" {

void* rio_open(const char* path, int writable) {
  Rio* r = new Rio();
  r->f = fopen(path, writable ? "wb" : "rb");
  if (!r->f) {
    delete r;
    return nullptr;
  }
  r->writable = writable != 0;
  r->iobuf.resize(kBufSize);
  setvbuf(r->f, r->iobuf.data(), _IOFBF, r->iobuf.size());
  return r;
}

void rio_close(void* h) {
  Rio* r = static_cast<Rio*>(h);
  if (!r) return;
  if (r->f) fclose(r->f);
  delete r;
}

long rio_tell(void* h) {
  Rio* r = static_cast<Rio*>(h);
  return r && r->f ? ftell(r->f) : -1;
}

int rio_seek(void* h, long offset) {
  Rio* r = static_cast<Rio*>(h);
  if (!r || !r->f) return -1;
  return fseek(r->f, offset, SEEK_SET);
}

int rio_flush(void* h) {
  Rio* r = static_cast<Rio*>(h);
  if (!r || !r->f) return -1;
  return fflush(r->f);
}

// Write one record, splitting payloads over 2^29-1 bytes into
// first/middle/last chunks.  Returns 0, or -1 on IO error.
int rio_write(void* h, const char* buf, long len) {
  Rio* r = static_cast<Rio*>(h);
  if (!r || !r->f || !r->writable) return -1;
  if (len <= static_cast<long>(kMaxChunk))
    return write_chunk(r->f, 0, buf, static_cast<uint32_t>(len));
  long pos = 0;
  bool first = true;
  while (pos < len) {
    long n = len - pos;
    if (n > static_cast<long>(kMaxChunk)) n = kMaxChunk;
    uint32_t cflag = first ? 1u : (pos + n >= len ? 3u : 2u);
    if (write_chunk(r->f, cflag, buf + pos, static_cast<uint32_t>(n)) != 0)
      return -1;
    first = false;
    pos += n;
  }
  return 0;
}

// Read the next record.  *out receives a malloc'd buffer (free with
// rio_free).  Returns payload length, -1 on EOF, -2 on corruption.
long rio_read(void* h, char** out) {
  Rio* r = static_cast<Rio*>(h);
  if (!r || !r->f || r->writable) return -2;
  return read_record(r->f, out);
}

void rio_free(char* buf) { free(buf); }

// Batched random-access read: n records at the given byte offsets, each
// on its own FILE* so reads run concurrently across `nthreads` workers
// (the prefetch half of the reference's threaded record loader).
// bufs[i] receives a malloc'd payload, lens[i] its length (-2 for a bad
// record).  Returns 0, or -1 if the file cannot be opened.
int rio_read_batch(const char* path, const long* offsets, int n,
                   char** bufs, long* lens, int nthreads) {
  if (nthreads < 1) nthreads = 1;
  if (nthreads > n) nthreads = n;
  std::vector<std::thread> pool;
  std::atomic<bool> open_failed{false};
  for (int t = 0; t < nthreads; ++t) {
    pool.emplace_back([&, t]() {
      FILE* f = fopen(path, "rb");
      if (!f) {
        open_failed = true;
        return;
      }
      std::vector<char> buf(kBufSize);
      setvbuf(f, buf.data(), _IOFBF, buf.size());
      for (int i = t; i < n; i += nthreads) {
        if (fseek(f, offsets[i], SEEK_SET) != 0) {
          lens[i] = -2;
          continue;
        }
        char* out = nullptr;
        long len = read_record(f, &out);
        bufs[i] = out;
        lens[i] = len;
      }
      fclose(f);
    });
  }
  for (auto& th : pool) th.join();
  return open_failed.load() ? -1 : 0;
}

}  // extern "C"
