// C-callable edge inference runtime (reference: src/c_api/c_predict_api.cc
// :: MXPredCreate/SetInput/Forward/GetOutput + amalgamation/).
//
// TPU-native edge answer: the training framework exports a standard ONNX
// artifact (mx.onnx.export_model, self-contained protobuf); this runtime
// is a dependency-free C++17 interpreter for the exported op set, built as
// one shared library with a flat C ABI -- no Python, no protobuf library,
// no BLAS.  The wire parsing below implements the protobuf subset ONNX
// uses (varints + length-delimited submessages) directly.
//
// Intended for CPU-side edge serving and as the C ABI surface (SURVEY L6);
// the datacenter path stays XLA.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

struct Tensor {
  std::vector<int64_t> shape;
  std::vector<float> data;
  int64_t numel() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
};

// ---------------------------------------------------------------------
// protobuf wire reader
// ---------------------------------------------------------------------

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      v |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 70) break;
    }
    ok = false;
    return 0;
  }

  bool next(uint32_t* field, uint32_t* wire, const uint8_t** payload,
            uint64_t* len) {
    if (p >= end || !ok) return false;
    uint64_t key = varint();
    if (!ok) return false;
    *field = uint32_t(key >> 3);
    *wire = uint32_t(key & 7);
    switch (*wire) {
      case 0:
        *len = varint();  // value itself
        *payload = nullptr;
        return ok;
      case 1:
        if (end - p < 8) return ok = false;
        *payload = p;
        *len = 8;
        p += 8;
        return true;
      case 2: {
        uint64_t n = varint();
        if (!ok || uint64_t(end - p) < n) return ok = false;
        *payload = p;
        *len = n;
        p += n;
        return true;
      }
      case 5:
        if (end - p < 4) return ok = false;
        *payload = p;
        *len = 4;
        p += 4;
        return true;
      default:
        return ok = false;
    }
  }
};

struct Attr {
  int64_t i = 0;
  float f = 0.f;
  std::string s;
  std::vector<int64_t> ints;
  std::vector<float> floats;
};

struct Node {
  std::string op;
  std::vector<std::string> inputs;
  std::vector<std::string> outputs;
  std::map<std::string, Attr> attrs;
};

struct Graph {
  std::vector<Node> nodes;
  std::map<std::string, Tensor> initializers;
  std::vector<std::string> inputs;   // non-initializer graph inputs
  std::vector<std::string> outputs;
};

std::string str_of(const uint8_t* p, uint64_t n) {
  return std::string(reinterpret_cast<const char*>(p), size_t(n));
}

bool parse_tensor(const uint8_t* buf, uint64_t len, std::string* name,
                  Tensor* t) {
  Reader r{buf, buf + len};
  uint32_t field, wire;
  const uint8_t* pl;
  uint64_t n;
  int32_t dtype = 1;
  const uint8_t* raw = nullptr;
  uint64_t rawlen = 0;
  std::vector<float> fdata;
  std::vector<int64_t> idata;
  while (r.next(&field, &wire, &pl, &n)) {
    switch (field) {
      case 1:  // dims (proto3 serializers emit repeated int64 packed)
        if (wire == 0) {
          t->shape.push_back(int64_t(n));
        } else if (wire == 2) {
          Reader rr{pl, pl + n};
          while (rr.p < rr.end && rr.ok)
            t->shape.push_back(int64_t(rr.varint()));
        }
        break;
      case 2:
        if (wire == 0) dtype = int32_t(n);
        break;
      case 4:  // float_data (packed or not)
        if (wire == 2)
          for (uint64_t i = 0; i + 4 <= n; i += 4) {
            float f;
            memcpy(&f, pl + i, 4);
            fdata.push_back(f);
          }
        else if (wire == 5) {
          float f;
          memcpy(&f, pl, 4);
          fdata.push_back(f);
        }
        break;
      case 7:  // int64_data
        if (wire == 0)
          idata.push_back(int64_t(n));
        else if (wire == 2) {
          Reader rr{pl, pl + n};
          while (rr.p < rr.end && rr.ok) idata.push_back(int64_t(rr.varint()));
        }
        break;
      case 8:
        if (wire == 2) *name = str_of(pl, n);
        break;
      case 9:
        if (wire == 2) {
          raw = pl;
          rawlen = n;
        }
        break;
      default:
        break;
    }
  }
  if (!r.ok) return false;
  int64_t numel = 1;
  for (auto d : t->shape) numel *= d;
  t->data.resize(size_t(numel));
  if (raw) {
    switch (dtype) {
      case 1:  // FLOAT
        if (rawlen < uint64_t(numel) * 4) return false;
        memcpy(t->data.data(), raw, size_t(numel) * 4);
        break;
      case 7: {  // INT64
        if (rawlen < uint64_t(numel) * 8) return false;
        for (int64_t i = 0; i < numel; ++i) {
          int64_t v;
          memcpy(&v, raw + i * 8, 8);
          t->data[size_t(i)] = float(v);
        }
        break;
      }
      case 6: {  // INT32
        if (rawlen < uint64_t(numel) * 4) return false;
        for (int64_t i = 0; i < numel; ++i) {
          int32_t v;
          memcpy(&v, raw + i * 4, 4);
          t->data[size_t(i)] = float(v);
        }
        break;
      }
      default:
        g_last_error = "unsupported tensor dtype " + std::to_string(dtype);
        return false;
    }
  } else if (!fdata.empty()) {
    if (int64_t(fdata.size()) < numel) return false;
    std::copy(fdata.begin(), fdata.begin() + numel, t->data.begin());
  } else if (!idata.empty()) {
    if (int64_t(idata.size()) < numel) return false;
    for (int64_t i = 0; i < numel; ++i) t->data[size_t(i)] = float(idata[i]);
  } else if (numel != 0) {
    return false;
  }
  return true;
}

bool parse_attr(const uint8_t* buf, uint64_t len, std::string* name,
                Attr* a) {
  Reader r{buf, buf + len};
  uint32_t field, wire;
  const uint8_t* pl;
  uint64_t n;
  while (r.next(&field, &wire, &pl, &n)) {
    switch (field) {
      case 1:
        if (wire == 2) *name = str_of(pl, n);
        break;
      case 2:
        if (wire == 5) {
          float f;
          memcpy(&f, pl, 4);
          a->f = f;
        }
        break;
      case 3:
        if (wire == 0) a->i = int64_t(n);
        break;
      case 4:
        if (wire == 2) a->s = str_of(pl, n);
        break;
      case 7:
        if (wire == 5) {
          float f;
          memcpy(&f, pl, 4);
          a->floats.push_back(f);
        } else if (wire == 2) {
          for (uint64_t i = 0; i + 4 <= n; i += 4) {
            float f;
            memcpy(&f, pl + i, 4);
            a->floats.push_back(f);
          }
        }
        break;
      case 8:
        if (wire == 0)
          a->ints.push_back(int64_t(n));
        else if (wire == 2) {
          Reader rr{pl, pl + n};
          while (rr.p < rr.end && rr.ok) a->ints.push_back(int64_t(rr.varint()));
        }
        break;
      default:
        break;
    }
  }
  return r.ok;
}

bool parse_node(const uint8_t* buf, uint64_t len, Node* node) {
  Reader r{buf, buf + len};
  uint32_t field, wire;
  const uint8_t* pl;
  uint64_t n;
  while (r.next(&field, &wire, &pl, &n)) {
    if (wire != 2) continue;  // all NodeProto fields we read are bytes
    switch (field) {
      case 1:
        node->inputs.push_back(str_of(pl, n));
        break;
      case 2:
        node->outputs.push_back(str_of(pl, n));
        break;
      case 4:
        node->op = str_of(pl, n);
        break;
      case 5: {
        std::string name;
        Attr a;
        if (!parse_attr(pl, n, &name, &a)) return false;
        node->attrs[name] = std::move(a);
        break;
      }
      default:
        break;
    }
  }
  return r.ok;
}

std::string value_info_name(const uint8_t* buf, uint64_t len) {
  Reader r{buf, buf + len};
  uint32_t field, wire;
  const uint8_t* pl;
  uint64_t n;
  while (r.next(&field, &wire, &pl, &n))
    if (field == 1 && wire == 2) return str_of(pl, n);
  return "";
}

bool parse_graph(const uint8_t* buf, uint64_t len, Graph* g) {
  Reader r{buf, buf + len};
  uint32_t field, wire;
  const uint8_t* pl;
  uint64_t n;
  std::vector<std::string> raw_inputs;
  while (r.next(&field, &wire, &pl, &n)) {
    if (wire != 2) continue;  // all GraphProto fields we read are bytes
    switch (field) {
      case 1: {
        Node node;
        if (!parse_node(pl, n, &node)) return false;
        g->nodes.push_back(std::move(node));
        break;
      }
      case 5: {
        std::string name;
        Tensor t;
        if (!parse_tensor(pl, n, &name, &t)) return false;
        g->initializers[name] = std::move(t);
        break;
      }
      case 11:
        raw_inputs.push_back(value_info_name(pl, n));
        break;
      case 12:
        g->outputs.push_back(value_info_name(pl, n));
        break;
      default:
        break;
    }
  }
  for (auto& name : raw_inputs)
    if (!g->initializers.count(name)) g->inputs.push_back(name);
  return r.ok;
}

bool parse_model(const uint8_t* buf, uint64_t len, Graph* g) {
  Reader r{buf, buf + len};
  uint32_t field, wire;
  const uint8_t* pl;
  uint64_t n;
  while (r.next(&field, &wire, &pl, &n))
    if (field == 7 && wire == 2) return parse_graph(pl, n, g);
  g_last_error = "no GraphProto in model";
  return false;
}

// ---------------------------------------------------------------------
// op kernels (NCHW, float32)
// ---------------------------------------------------------------------

std::vector<int64_t> attr_ints(const Node& nd, const char* key,
                               std::vector<int64_t> dflt) {
  auto it = nd.attrs.find(key);
  return it == nd.attrs.end() || it->second.ints.empty() ? dflt
                                                         : it->second.ints;
}

int64_t attr_i(const Node& nd, const char* key, int64_t dflt) {
  auto it = nd.attrs.find(key);
  return it == nd.attrs.end() ? dflt : it->second.i;
}

float attr_f(const Node& nd, const char* key, float dflt) {
  auto it = nd.attrs.find(key);
  return it == nd.attrs.end() ? dflt : it->second.f;
}

bool conv2d(const Node& nd, const Tensor& x, const Tensor& w,
            const Tensor* bias, Tensor* y) {
  if (x.shape.size() != 4 || w.shape.size() != 4) {
    g_last_error = "Conv: only 2-D convolution supported";
    return false;
  }
  int64_t N = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
  int64_t O = w.shape[0], CI = w.shape[1], KH = w.shape[2], KW = w.shape[3];
  int64_t groups = attr_i(nd, "group", 1);
  auto strides = attr_ints(nd, "strides", {1, 1});
  auto dil = attr_ints(nd, "dilations", {1, 1});
  auto pads = attr_ints(nd, "pads", {0, 0, 0, 0});
  if (pads.size() >= 4 && (pads[0] != pads[2] || pads[1] != pads[3])) {
    g_last_error = "Conv: asymmetric pads unsupported";
    return false;
  }
  int64_t ph = pads[0], pw = pads[1];
  int64_t OH = (H + 2 * ph - dil[0] * (KH - 1) - 1) / strides[0] + 1;
  int64_t OW = (W + 2 * pw - dil[1] * (KW - 1) - 1) / strides[1] + 1;
  if (C != CI * groups) {
    g_last_error = "Conv: channel mismatch";
    return false;
  }
  y->shape = {N, O, OH, OW};
  y->data.assign(size_t(N * O * OH * OW), 0.f);
  int64_t opg = O / groups;
  for (int64_t nidx = 0; nidx < N; ++nidx)
    for (int64_t o = 0; o < O; ++o) {
      int64_t gidx = o / opg;
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float acc = bias ? bias->data[size_t(o)] : 0.f;
          for (int64_t ci = 0; ci < CI; ++ci) {
            int64_t c = gidx * CI + ci;
            for (int64_t kh = 0; kh < KH; ++kh) {
              int64_t ih = oh * strides[0] + kh * dil[0] - ph;
              if (ih < 0 || ih >= H) continue;
              const float* xrow =
                  &x.data[size_t(((nidx * C + c) * H + ih) * W)];
              const float* wrow =
                  &w.data[size_t(((o * CI + ci) * KH + kh) * KW)];
              for (int64_t kw = 0; kw < KW; ++kw) {
                int64_t iw = ow * strides[1] + kw * dil[1] - pw;
                if (iw < 0 || iw >= W) continue;
                acc += xrow[iw] * wrow[kw];
              }
            }
          }
          y->data[size_t(((nidx * O + o) * OH + oh) * OW + ow)] = acc;
        }
    }
  return true;
}

bool pool2d(const Node& nd, const Tensor& x, Tensor* y, bool is_max,
            bool global_pool) {
  int64_t N = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
  std::vector<int64_t> kernel, strides, pads;
  bool ceil_mode = false;
  bool count_include_pad = true;
  if (global_pool) {
    kernel = {H, W};
    strides = {1, 1};
    pads = {0, 0, 0, 0};
  } else {
    kernel = attr_ints(nd, "kernel_shape", {1, 1});
    strides = attr_ints(nd, "strides", {1, 1});
    pads = attr_ints(nd, "pads", {0, 0, 0, 0});
    ceil_mode = attr_i(nd, "ceil_mode", 0) != 0;
    count_include_pad = attr_i(nd, "count_include_pad", 1) != 0;
  }
  if (pads.size() >= 4 && (pads[0] != pads[2] || pads[1] != pads[3])) {
    g_last_error = "Pool: asymmetric pads unsupported";
    return false;
  }
  int64_t ph = pads[0], pw = pads[1];
  auto osz = [&](int64_t in, int64_t k, int64_t s, int64_t p) {
    int64_t span = in + 2 * p - k;
    return (ceil_mode ? (span + s - 1) / s : span / s) + 1;
  };
  int64_t OH = osz(H, kernel[0], strides[0], ph);
  int64_t OW = osz(W, kernel[1], strides[1], pw);
  y->shape = {N, C, OH, OW};
  y->data.assign(size_t(N * C * OH * OW), 0.f);
  for (int64_t nidx = 0; nidx < N; ++nidx)
    for (int64_t c = 0; c < C; ++c)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float best = -3.4e38f;
          float sum = 0.f;
          int64_t cnt = 0;
          for (int64_t kh = 0; kh < kernel[0]; ++kh) {
            int64_t ih = oh * strides[0] + kh - ph;
            if (ih < 0 || ih >= H) continue;
            for (int64_t kw = 0; kw < kernel[1]; ++kw) {
              int64_t iw = ow * strides[1] + kw - pw;
              if (iw < 0 || iw >= W) continue;
              float v = x.data[size_t(((nidx * C + c) * H + ih) * W + iw)];
              best = v > best ? v : best;
              sum += v;
              cnt++;
            }
          }
          float out;
          if (is_max)
            out = cnt ? best : 0.f;
          else if (count_include_pad)
            out = sum / float(kernel[0] * kernel[1]);
          else
            out = cnt ? sum / float(cnt) : 0.f;
          y->data[size_t(((nidx * C + c) * OH + oh) * OW + ow)] = out;
        }
  return true;
}

void gemm(const Tensor& a, const Tensor& b, const Tensor* bias, bool transB,
          Tensor* y) {
  int64_t M = a.shape[0], K = a.shape[1];
  int64_t N = transB ? b.shape[0] : b.shape[1];
  y->shape = {M, N};
  y->data.assign(size_t(M * N), 0.f);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t n = 0; n < N; ++n) {
      float acc = bias ? bias->data[size_t(n % int64_t(bias->data.size()))]
                       : 0.f;
      const float* arow = &a.data[size_t(m * K)];
      if (transB) {
        const float* brow = &b.data[size_t(n * K)];
        for (int64_t k = 0; k < K; ++k) acc += arow[k] * brow[k];
      } else {
        for (int64_t k = 0; k < K; ++k)
          acc += arow[k] * b.data[size_t(k * N + n)];
      }
      y->data[size_t(m * N + n)] = acc;
    }
}

// numpy-style broadcast binary op
bool broadcast_binop(const Tensor& a, const Tensor& b, int kind, Tensor* y) {
  size_t nd = std::max(a.shape.size(), b.shape.size());
  std::vector<int64_t> sa(nd, 1), sb(nd, 1), so(nd, 1);
  std::copy(a.shape.begin(), a.shape.end(),
            sa.begin() + (nd - a.shape.size()));
  std::copy(b.shape.begin(), b.shape.end(),
            sb.begin() + (nd - b.shape.size()));
  for (size_t i = 0; i < nd; ++i) {
    if (sa[i] != sb[i] && sa[i] != 1 && sb[i] != 1) {
      g_last_error = "broadcast shape mismatch";
      return false;
    }
    so[i] = std::max(sa[i], sb[i]);
  }
  y->shape = so;
  int64_t total = 1;
  for (auto d : so) total *= d;
  y->data.resize(size_t(total));
  std::vector<int64_t> stra(nd), strb(nd);
  int64_t ra = 1, rb = 1;
  for (size_t i = nd; i-- > 0;) {
    stra[i] = (sa[i] == 1) ? 0 : ra;
    strb[i] = (sb[i] == 1) ? 0 : rb;
    ra *= sa[i];
    rb *= sb[i];
  }
  std::vector<int64_t> idx(nd, 0);
  for (int64_t flat = 0; flat < total; ++flat) {
    int64_t ia = 0, ib = 0;
    for (size_t i = 0; i < nd; ++i) {
      ia += idx[i] * stra[i];
      ib += idx[i] * strb[i];
    }
    float va = a.data[size_t(ia)], vb = b.data[size_t(ib)];
    float out = 0;
    switch (kind) {
      case 0: out = va + vb; break;
      case 1: out = va - vb; break;
      case 2: out = va * vb; break;
      case 3: out = va / vb; break;
    }
    y->data[size_t(flat)] = out;
    for (size_t i = nd; i-- > 0;) {
      if (++idx[i] < so[i]) break;
      idx[i] = 0;
    }
  }
  return true;
}

// ---------------------------------------------------------------------
// the predictor
// ---------------------------------------------------------------------

struct Predictor {
  Graph graph;
  std::map<std::string, Tensor> env;
  std::vector<Tensor> outputs;
  bool ran = false;

  const Tensor* get(const std::string& name) {
    auto it = env.find(name);
    if (it != env.end()) return &it->second;
    auto it2 = graph.initializers.find(name);
    if (it2 != graph.initializers.end()) return &it2->second;
    return nullptr;
  }

  bool run() {
    for (auto& nd : graph.nodes) {
      std::vector<const Tensor*> in;
      for (auto& nm : nd.inputs) {
        const Tensor* t = get(nm);
        if (!t && !nm.empty()) {
          g_last_error = "missing tensor " + nm + " for op " + nd.op;
          return false;
        }
        in.push_back(t);
      }
      Tensor out;
      const std::string& op = nd.op;
      bool ok = true;
      if (op == "Conv") {
        ok = conv2d(nd, *in[0], *in[1], in.size() > 2 ? in[2] : nullptr,
                    &out);
      } else if (op == "MaxPool") {
        ok = pool2d(nd, *in[0], &out, true, false);
      } else if (op == "AveragePool") {
        ok = pool2d(nd, *in[0], &out, false, false);
      } else if (op == "GlobalAveragePool") {
        ok = pool2d(nd, *in[0], &out, false, true);
      } else if (op == "GlobalMaxPool") {
        ok = pool2d(nd, *in[0], &out, true, true);
      } else if (op == "Gemm") {
        if (attr_f(nd, "alpha", 1.f) != 1.f ||
            attr_f(nd, "beta", 1.f) != 1.f ||
            attr_i(nd, "transA", 0) != 0) {
          g_last_error = "Gemm: alpha/beta != 1 or transA unsupported";
          ok = false;
        } else {
          gemm(*in[0], *in[1], in.size() > 2 ? in[2] : nullptr,
               attr_i(nd, "transB", 0) != 0, &out);
        }
      } else if (op == "MatMul") {
        if (in[0]->shape.size() != 2 || in[1]->shape.size() != 2) {
          g_last_error = "MatMul: only rank-2 supported";
          ok = false;
        } else {
          gemm(*in[0], *in[1], nullptr, false, &out);
        }
      } else if (op == "BatchNormalization") {
        const Tensor &x = *in[0], &sc = *in[1], &b = *in[2], &mu = *in[3],
                     &var = *in[4];
        float eps = attr_f(nd, "epsilon", 1e-5f);
        out.shape = x.shape;
        out.data.resize(x.data.size());
        int64_t C = x.shape.size() > 1 ? x.shape[1] : x.shape[0];
        int64_t inner = 1;
        for (size_t i = 2; i < x.shape.size(); ++i) inner *= x.shape[i];
        int64_t N = x.shape.empty() ? 1 : x.shape[0];
        for (int64_t nidx = 0; nidx < N; ++nidx)
          for (int64_t c = 0; c < C; ++c) {
            float s = sc.data[size_t(c)] /
                      std::sqrt(var.data[size_t(c)] + eps);
            float off = b.data[size_t(c)] - mu.data[size_t(c)] * s;
            float* dst = &out.data[size_t((nidx * C + c) * inner)];
            const float* src = &x.data[size_t((nidx * C + c) * inner)];
            for (int64_t i = 0; i < inner; ++i) dst[i] = src[i] * s + off;
          }
      } else if (op == "Relu") {
        out.shape = in[0]->shape;
        out.data.resize(in[0]->data.size());
        for (size_t i = 0; i < out.data.size(); ++i)
          out.data[i] = in[0]->data[i] > 0 ? in[0]->data[i] : 0;
      } else if (op == "Sigmoid" || op == "Tanh" || op == "Softplus" ||
                 op == "Sqrt" || op == "Exp" || op == "Log" ||
                 op == "Abs" || op == "Neg" || op == "Identity" ||
                 op == "Floor" || op == "Ceil" || op == "Erf") {
        out.shape = in[0]->shape;
        out.data.resize(in[0]->data.size());
        for (size_t i = 0; i < out.data.size(); ++i) {
          float v = in[0]->data[i];
          if (op == "Sigmoid") v = 1.f / (1.f + std::exp(-v));
          else if (op == "Tanh") v = std::tanh(v);
          else if (op == "Softplus") v = std::log1p(std::exp(v));
          else if (op == "Sqrt") v = std::sqrt(v);
          else if (op == "Exp") v = std::exp(v);
          else if (op == "Log") v = std::log(v);
          else if (op == "Abs") v = std::fabs(v);
          else if (op == "Neg") v = -v;
          else if (op == "Floor") v = std::floor(v);
          else if (op == "Ceil") v = std::ceil(v);
          else if (op == "Erf") v = std::erf(v);
          out.data[i] = v;
        }
      } else if (op == "LeakyRelu" || op == "Elu") {
        float alpha = attr_f(nd, "alpha", op == "Elu" ? 1.0f : 0.01f);
        out.shape = in[0]->shape;
        out.data.resize(in[0]->data.size());
        for (size_t i = 0; i < out.data.size(); ++i) {
          float v = in[0]->data[i];
          out.data[i] = v > 0 ? v
                              : (op == "Elu" ? alpha * std::expm1(v)
                                             : alpha * v);
        }
      } else if (op == "Add" || op == "Sub" || op == "Mul" || op == "Div") {
        int kind = op == "Add" ? 0 : op == "Sub" ? 1 : op == "Mul" ? 2 : 3;
        ok = broadcast_binop(*in[0], *in[1], kind, &out);
      } else if (op == "Softmax") {
        int64_t axis = attr_i(nd, "axis", -1);
        const Tensor& x = *in[0];
        size_t nd_ = x.shape.size();
        if (axis < 0) axis += int64_t(nd_);
        if (axis != int64_t(nd_) - 1) {
          g_last_error = "Softmax: only last axis supported";
          ok = false;
        } else {
          out.shape = x.shape;
          out.data.resize(x.data.size());
          int64_t inner = x.shape.back();
          int64_t outer = x.numel() / inner;
          for (int64_t o = 0; o < outer; ++o) {
            const float* src = &x.data[size_t(o * inner)];
            float* dst = &out.data[size_t(o * inner)];
            float mx = src[0];
            for (int64_t i = 1; i < inner; ++i) mx = std::max(mx, src[i]);
            float tot = 0;
            for (int64_t i = 0; i < inner; ++i) {
              dst[i] = std::exp(src[i] - mx);
              tot += dst[i];
            }
            for (int64_t i = 0; i < inner; ++i) dst[i] /= tot;
          }
        }
      } else if (op == "Flatten") {
        const Tensor& x = *in[0];
        int64_t axis = attr_i(nd, "axis", 1);
        int64_t outer = 1, inner = 1;
        for (size_t i = 0; i < x.shape.size(); ++i)
          (int64_t(i) < axis ? outer : inner) *= x.shape[i];
        out.shape = {outer, inner};
        out.data = x.data;
      } else if (op == "Reshape") {
        const Tensor& x = *in[0];
        const Tensor& shp = *in[1];
        std::vector<int64_t> ns;
        int64_t known = 1, infer = -1;
        for (size_t i = 0; i < shp.data.size(); ++i) {
          int64_t d = int64_t(shp.data[i]);
          if (d == 0) d = x.shape[i];
          if (d == -1) {
            infer = int64_t(ns.size());
            ns.push_back(1);
          } else {
            ns.push_back(d);
            known *= d;
          }
        }
        if (infer >= 0) ns[size_t(infer)] = x.numel() / known;
        out.shape = ns;
        out.data = x.data;
      } else if (op == "Transpose") {
        const Tensor& x = *in[0];
        auto perm = attr_ints(nd, "perm", {});
        size_t nd_ = x.shape.size();
        if (perm.empty())
          for (size_t i = nd_; i-- > 0;) perm.push_back(int64_t(i));
        out.shape.resize(nd_);
        for (size_t i = 0; i < nd_; ++i)
          out.shape[i] = x.shape[size_t(perm[i])];
        out.data.resize(x.data.size());
        std::vector<int64_t> strides(nd_, 1), ostrides(nd_, 1);
        for (size_t i = nd_ - 1; i-- > 0;)
          strides[i] = strides[i + 1] * x.shape[i + 1];
        for (size_t i = nd_ - 1; i-- > 0;)
          ostrides[i] = ostrides[i + 1] * out.shape[i + 1];
        std::vector<int64_t> idx(nd_, 0);
        for (int64_t flat = 0; flat < x.numel(); ++flat) {
          int64_t src = 0;
          for (size_t i = 0; i < nd_; ++i)
            src += idx[i] * strides[size_t(perm[i])];
          out.data[size_t(flat)] = x.data[size_t(src)];
          for (size_t i = nd_; i-- > 0;) {
            if (++idx[i] < out.shape[i]) break;
            idx[i] = 0;
          }
        }
      } else if (op == "Concat") {
        int64_t axis = attr_i(nd, "axis", 1);
        const Tensor& first = *in[0];
        size_t nd_ = first.shape.size();
        if (axis < 0) axis += int64_t(nd_);
        out.shape = first.shape;
        int64_t cat = 0;
        for (auto* t : in) cat += t->shape[size_t(axis)];
        out.shape[size_t(axis)] = cat;
        int64_t outer = 1, inner = 1;
        for (int64_t i = 0; i < axis; ++i) outer *= first.shape[size_t(i)];
        for (size_t i = size_t(axis) + 1; i < nd_; ++i)
          inner *= first.shape[i];
        out.data.resize(size_t(outer * cat * inner));
        int64_t off = 0;
        for (auto* t : in) {
          int64_t ax = t->shape[size_t(axis)];
          for (int64_t o = 0; o < outer; ++o)
            memcpy(&out.data[size_t((o * cat + off) * inner)],
                   &t->data[size_t(o * ax * inner)],
                   size_t(ax * inner) * 4);
          off += ax;
        }
      } else if (op == "Clip") {
        float lo = in.size() > 1 && in[1] ? in[1]->data[0]
                                          : attr_f(nd, "min", -3.4e38f);
        float hi = in.size() > 2 && in[2] ? in[2]->data[0]
                                          : attr_f(nd, "max", 3.4e38f);
        out.shape = in[0]->shape;
        out.data.resize(in[0]->data.size());
        for (size_t i = 0; i < out.data.size(); ++i)
          out.data[i] = std::min(hi, std::max(lo, in[0]->data[i]));
      } else if (op == "Gather") {
        // axis-0 gather (Embedding)
        const Tensor& table = *in[0];
        const Tensor& idxs = *in[1];
        int64_t row = table.numel() / table.shape[0];
        out.shape = idxs.shape;
        for (size_t i = 1; i < table.shape.size(); ++i)
          out.shape.push_back(table.shape[i]);
        out.data.resize(size_t(idxs.numel() * row));
        for (int64_t i = 0; i < idxs.numel(); ++i)
          memcpy(&out.data[size_t(i * row)],
                 &table.data[size_t(int64_t(idxs.data[size_t(i)]) * row)],
                 size_t(row) * 4);
      } else if (op == "Unsqueeze") {
        const Tensor& x = *in[0];
        int64_t ax = in.size() > 1 && in[1] ? int64_t(in[1]->data[0])
                                            : attr_ints(nd, "axes", {0})[0];
        out.shape = x.shape;
        if (ax < 0) ax += int64_t(x.shape.size()) + 1;
        out.shape.insert(out.shape.begin() + ax, 1);
        out.data = x.data;
      } else {
        g_last_error = "unsupported op " + op;
        ok = false;
      }
      if (!ok) return false;
      env[nd.outputs[0]] = std::move(out);
    }
    outputs.clear();
    for (auto& nm : graph.outputs) {
      const Tensor* t = get(nm);
      if (!t) {
        g_last_error = "missing graph output " + nm;
        return false;
      }
      outputs.push_back(*t);
    }
    ran = true;
    return true;
  }
};

// ---------------------------------------------------------------------
// .params container reader (reference: c_predict_api.h :: MXNDListCreate
// over src/ndarray/ndarray.cc :: NDArray::Load).  Same dependency-free
// contract as the ONNX runtime: parameter files load with no Python in
// the loop.  Layout (little-endian; see mxnet_tpu/ndarray/ndarray.py
// and tests/test_params_format.py, which lock it byte-for-byte):
//   u64 list magic 0x112 | u64 reserved | u64 count
//   per array: u32 magic 0xF993FAC9 | i32 stype(0=dense) | u32 ndim |
//              i64*ndim dims | i32 dev_type + i32 dev_id | i32 dtype
//              flag | raw element bytes
//   u64 name count | per name: u64 byte length + utf-8
// ---------------------------------------------------------------------

struct NDList {
  std::vector<std::string> names;
  std::vector<Tensor> arrays;
};

struct LEReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  bool take(void* dst, size_t n) {
    if (!ok || size_t(end - p) < n) {
      ok = false;
      return false;
    }
    memcpy(dst, p, n);
    p += n;
    return true;
  }
  uint64_t u64() { uint64_t v = 0; take(&v, 8); return v; }
  uint32_t u32() { uint32_t v = 0; take(&v, 4); return v; }
  int32_t i32() { int32_t v = 0; take(&v, 4); return v; }
  int64_t i64() { int64_t v = 0; take(&v, 8); return v; }
};

float half_to_float(uint16_t h) {
  uint32_t sign = uint32_t(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t man = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;                       // +-0
    } else {                             // subnormal: renormalize
      uint32_t e = 127 - 15 + 1;
      while (!(man & 0x400)) { man <<= 1; --e; }
      bits = sign | (e << 23) | ((man & 0x3FF) << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (man << 13);   // inf/nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float f;
  memcpy(&f, &bits, 4);
  return f;
}

template <typename T>
bool read_as_float(LEReader* r, int64_t n, std::vector<float>* out) {
  std::vector<T> tmp(static_cast<size_t>(n));
  if (!r->take(tmp.data(), size_t(n) * sizeof(T))) return false;
  for (int64_t i = 0; i < n; ++i) (*out)[size_t(i)] = float(tmp[size_t(i)]);
  return true;
}

bool parse_params(const uint8_t* data, uint64_t len, NDList* out) {
  LEReader r{data, data + len};
  if (r.u64() != 0x112) {
    g_last_error = "bad .params list magic";
    return false;
  }
  r.u64();  // reserved
  uint64_t count = r.u64();
  // each serialized array needs >= 24 bytes of header alone: bounding
  // count by the remaining bytes stops a tiny crafted file from
  // forcing a huge up-front allocation
  if (!r.ok || count > uint64_t(r.end - r.p) / 24) {
    g_last_error = "corrupt .params header";
    return false;
  }
  out->arrays.resize(size_t(count));
  for (auto& t : out->arrays) {
    if (r.u32() != 0xF993FAC9u) {
      g_last_error = "bad ndarray magic in .params";
      return false;
    }
    if (r.i32() != 0) {
      g_last_error = ".params: only dense arrays supported";
      return false;
    }
    uint32_t ndim = r.u32();
    if (!r.ok || ndim > 32) {
      g_last_error = ".params: corrupt ndarray rank";
      return false;
    }
    t.shape.resize(ndim);
    // overflow-checked element count: crafted dims like [2^32, 2^32]
    // would wrap numel() to a small value and desynchronize the size
    // check from the shape handed to the C caller
    int64_t n = 1;
    for (auto& d : t.shape) {
      d = r.i64();
      if (!r.ok || d < 0 ||
          (d != 0 && n > INT64_MAX / (d ? d : 1))) {
        g_last_error = ".params: corrupt ndarray dims";
        return false;
      }
      n *= d;
    }
    r.i32();
    r.i32();  // dev_type, dev_id
    int32_t flag = r.i32();
    if (!r.ok || uint64_t(n) > uint64_t(r.end - r.p)) {
      g_last_error = ".params: corrupt ndarray size";
      return false;
    }
    t.data.resize(size_t(n));
    bool good = true;
    switch (flag) {
      case 0:   // float32
        good = r.take(t.data.data(), size_t(n) * 4);
        break;
      case 1: good = read_as_float<double>(&r, n, &t.data); break;
      case 2: {  // float16
        std::vector<uint16_t> tmp(static_cast<size_t>(n));
        good = r.take(tmp.data(), size_t(n) * 2);
        if (good)
          for (int64_t i = 0; i < n; ++i)
            t.data[size_t(i)] = half_to_float(tmp[size_t(i)]);
        break;
      }
      case 3: good = read_as_float<uint8_t>(&r, n, &t.data); break;
      case 4: good = read_as_float<int32_t>(&r, n, &t.data); break;
      case 5: good = read_as_float<int8_t>(&r, n, &t.data); break;
      case 6: good = read_as_float<int64_t>(&r, n, &t.data); break;
      case 100: {  // bfloat16: high 16 bits of a float32
        std::vector<uint16_t> tmp(static_cast<size_t>(n));
        good = r.take(tmp.data(), size_t(n) * 2);
        if (good)
          for (int64_t i = 0; i < n; ++i) {
            uint32_t bits = uint32_t(tmp[size_t(i)]) << 16;
            memcpy(&t.data[size_t(i)], &bits, 4);
          }
        break;
      }
      default:
        g_last_error = ".params: unsupported dtype flag";
        return false;
    }
    if (!good) {
      g_last_error = ".params: truncated tensor data";
      return false;
    }
  }
  uint64_t nnames = r.u64();
  if (!r.ok || (nnames != 0 && nnames != count)) {
    g_last_error = ".params: corrupt name table";
    return false;
  }
  out->names.resize(size_t(nnames));
  for (auto& s : out->names) {
    uint64_t ln = r.u64();
    if (!r.ok || ln > uint64_t(r.end - r.p)) {
      g_last_error = ".params: corrupt name entry";
      return false;
    }
    s.assign(reinterpret_cast<const char*>(r.p), size_t(ln));
    r.p += ln;
  }
  return r.ok;
}

}  // namespace

// ---------------------------------------------------------------------
// C ABI (reference: c_predict_api.h)
// ---------------------------------------------------------------------

extern "C" {

typedef void* PredictorHandle;

const char* MXPredGetLastError() { return g_last_error.c_str(); }

int MXPredCreate(const char* model_bytes, int64_t model_len,
                 PredictorHandle* out) {
  auto pred = std::make_unique<Predictor>();
  if (!parse_model(reinterpret_cast<const uint8_t*>(model_bytes),
                   uint64_t(model_len), &pred->graph)) {
    if (g_last_error.empty()) g_last_error = "malformed ONNX model";
    return -1;
  }
  *out = pred.release();
  return 0;
}

int MXPredCreateFromFile(const char* path, PredictorHandle* out) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    g_last_error = std::string("cannot open ") + path;
    return -1;
  }
  fseek(f, 0, SEEK_END);
  long len = ftell(f);
  if (len < 0) {
    fclose(f);
    g_last_error = "cannot determine file size";
    return -1;
  }
  fseek(f, 0, SEEK_SET);
  try {
    std::vector<char> buf(static_cast<size_t>(len), 0);
    size_t got = fread(buf.data(), 1, size_t(len), f);
    fclose(f);
    if (got != size_t(len)) {
      g_last_error = "short read";
      return -1;
    }
    return MXPredCreate(buf.data(), len, out);
  } catch (const std::exception& e) {
    fclose(f);
    g_last_error = e.what();
    return -1;
  }
}

int MXPredSetInput(PredictorHandle h, const char* name, const float* data,
                   const int64_t* shape, int ndim) {
  auto* pred = static_cast<Predictor*>(h);
  Tensor t;
  t.shape.assign(shape, shape + ndim);
  t.data.assign(data, data + t.numel());
  std::string nm = name && name[0] ? name
                                   : (pred->graph.inputs.empty()
                                          ? std::string("data")
                                          : pred->graph.inputs[0]);
  pred->env[nm] = std::move(t);
  return 0;
}

int MXPredForward(PredictorHandle h) {
  auto* pred = static_cast<Predictor*>(h);
  return pred->run() ? 0 : -1;
}

int MXPredGetOutputShape(PredictorHandle h, int index, int64_t* shape,
                         int* ndim) {
  auto* pred = static_cast<Predictor*>(h);
  if (!pred->ran || index < 0 ||
      size_t(index) >= pred->outputs.size()) {
    g_last_error = "no such output (forward not run?)";
    return -1;
  }
  const Tensor& t = pred->outputs[size_t(index)];
  *ndim = int(t.shape.size());
  if (shape)
    for (size_t i = 0; i < t.shape.size(); ++i) shape[i] = t.shape[i];
  return 0;
}

int MXPredGetOutput(PredictorHandle h, int index, float* out,
                    int64_t size) {
  auto* pred = static_cast<Predictor*>(h);
  if (!pred->ran || index < 0 ||
      size_t(index) >= pred->outputs.size()) {
    g_last_error = "no such output (forward not run?)";
    return -1;
  }
  const Tensor& t = pred->outputs[size_t(index)];
  if (size < t.numel()) {
    g_last_error = "output buffer too small";
    return -1;
  }
  memcpy(out, t.data.data(), size_t(t.numel()) * 4);
  return 0;
}

void MXPredFree(PredictorHandle h) { delete static_cast<Predictor*>(h); }

// -- .params list ABI (reference: c_predict_api.h :: MXNDListCreate /
// MXNDListGet / MXNDListFree; values are exposed as float like the
// reference, whatever the stored dtype) ------------------------------

typedef void* NDListHandle;

int MXNDListCreate(const char* nd_file_bytes, int64_t nd_file_size,
                   NDListHandle* out, int64_t* out_length) {
  try {
    auto list = std::make_unique<NDList>();
    if (!parse_params(reinterpret_cast<const uint8_t*>(nd_file_bytes),
                      uint64_t(nd_file_size), list.get())) {
      if (g_last_error.empty()) g_last_error = "malformed .params file";
      return -1;
    }
    if (out_length) *out_length = int64_t(list->arrays.size());
    *out = list.release();
    return 0;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
}

int MXNDListCreateFromFile(const char* path, NDListHandle* out,
                           int64_t* out_length) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    g_last_error = std::string("cannot open ") + path;
    return -1;
  }
  fseek(f, 0, SEEK_END);
  long len = ftell(f);
  if (len < 0) {
    fclose(f);
    g_last_error = "cannot determine file size";
    return -1;
  }
  fseek(f, 0, SEEK_SET);
  try {
    std::vector<char> buf(static_cast<size_t>(len), 0);
    size_t got = fread(buf.data(), 1, size_t(len), f);
    fclose(f);
    if (got != size_t(len)) {
      g_last_error = "short read";
      return -1;
    }
    return MXNDListCreate(buf.data(), len, out, out_length);
  } catch (const std::exception& e) {
    fclose(f);
    g_last_error = e.what();
    return -1;
  }
}

int MXNDListGet(NDListHandle h, int64_t index, const char** out_key,
                const float** out_data, const int64_t** out_shape,
                int* out_ndim) {
  auto* list = static_cast<NDList*>(h);
  if (index < 0 || size_t(index) >= list->arrays.size()) {
    g_last_error = "MXNDListGet: index out of range";
    return -1;
  }
  const Tensor& t = list->arrays[size_t(index)];
  if (out_key)
    *out_key = size_t(index) < list->names.size()
                   ? list->names[size_t(index)].c_str()
                   : "";
  if (out_data) *out_data = t.data.data();
  if (out_shape) *out_shape = t.shape.data();
  if (out_ndim) *out_ndim = int(t.shape.size());
  return 0;
}

void MXNDListFree(NDListHandle h) { delete static_cast<NDList*>(h); }

}  // extern "C"
