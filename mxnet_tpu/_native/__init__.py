"""Native runtime components, compiled on demand.

The C++ sources live next to this file; at first import they are built
with the system toolchain (g++ -O2 -shared -fPIC) into a cached shared
library, loaded via ctypes.  No native toolchain, or a failed build,
degrades gracefully: callers get ``None`` and use the pure-Python path.
Set ``MXNET_TPU_NATIVE=0`` to force the Python path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import warnings

_LIB = None
_TRIED = False

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "recordio_native.cc")


def _cache_dir():
    d = os.environ.get("MXNET_TPU_NATIVE_CACHE",
                       os.path.expanduser("~/.cache/mxnet_tpu/native"))
    os.makedirs(d, exist_ok=True)
    return d


def _build(src, out):
    """Compile under an flock, into a temp file renamed atomically into
    place: N launcher workers may import cold-cache simultaneously, and
    a half-written .so must never be dlopen'd (or truncate a mapping
    another process already holds)."""
    import fcntl
    lock_path = out + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            # another process may have finished the build while we waited
            if os.path.exists(out) and \
                    os.path.getmtime(out) >= os.path.getmtime(src):
                return
            tmp = "%s.%d.tmp" % (out, os.getpid())
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   "-pthread", src, "-o", tmp]
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120)
            if proc.returncode != 0:
                raise RuntimeError("native build failed:\n%s"
                                   % proc.stderr[-2000:])
            os.replace(tmp, out)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def available():
    """Cheap probe: is a current .so already built AND loadable?  Never
    compiles -- diagnostics (runtime.Features) must not block on g++."""
    if _LIB is not None:
        return True
    if os.environ.get("MXNET_TPU_NATIVE", "1") == "0":
        return False
    so = os.path.join(_cache_dir(), "librecordio_native.so")
    if not (os.path.exists(so)
            and os.path.getmtime(so) >= os.path.getmtime(_SRC)):
        return False
    try:
        ctypes.CDLL(so)   # a stale half-written .so must not report ✔
        return True
    except OSError:
        return False


def load():
    """Return the loaded native library, or None when unavailable."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("MXNET_TPU_NATIVE", "1") == "0":
        return None
    try:
        so = os.path.join(_cache_dir(), "librecordio_native.so")
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(_SRC)):
            _build(_SRC, so)
        lib = ctypes.CDLL(so)
        lib.rio_open.restype = ctypes.c_void_p
        lib.rio_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.rio_close.argtypes = [ctypes.c_void_p]
        lib.rio_tell.restype = ctypes.c_long
        lib.rio_tell.argtypes = [ctypes.c_void_p]
        lib.rio_seek.restype = ctypes.c_int
        lib.rio_seek.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.rio_flush.restype = ctypes.c_int
        lib.rio_flush.argtypes = [ctypes.c_void_p]
        lib.rio_write.restype = ctypes.c_int
        lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_long]
        # out-pointers are void*: a c_char_p restype/arg would make
        # ctypes copy to Python bytes and lose the malloc'd pointer,
        # so rio_free would free a Python-owned buffer (heap abort)
        lib.rio_read.restype = ctypes.c_long
        lib.rio_read.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_void_p)]
        lib.rio_free.argtypes = [ctypes.c_void_p]
        lib.rio_read_batch.restype = ctypes.c_int
        lib.rio_read_batch.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_long), ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_long),
            ctypes.c_int]
        _LIB = lib
    except Exception as e:  # no toolchain / build error: Python fallback
        warnings.warn("mxnet_tpu native components unavailable (%s); "
                      "using pure-Python recordio" % e)
        _LIB = None
    return _LIB


# ----------------------------------------------------------------------
# C predict runtime (predict_native.cc -- reference: c_predict_api.cc)
# ----------------------------------------------------------------------

_PRED_LIB = None
_PRED_TRIED = False

_PRED_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "predict_native.cc")


def predict_so_path():
    """Path the predict runtime builds to (for linking C consumers)."""
    return os.path.join(_cache_dir(), "libmxtpu_predict.so")


def load_predict():
    """Build-on-demand loader for the C predict runtime; returns the
    ctypes library or None (no toolchain / build failure)."""
    global _PRED_LIB, _PRED_TRIED
    if _PRED_TRIED:
        return _PRED_LIB
    _PRED_TRIED = True
    if os.environ.get("MXNET_TPU_NATIVE", "1") == "0":
        return None
    try:
        so = predict_so_path()
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(_PRED_SRC)):
            _build(_PRED_SRC, so)
        lib = ctypes.CDLL(so)
        lib.MXPredGetLastError.restype = ctypes.c_char_p
        lib.MXPredCreate.restype = ctypes.c_int
        lib.MXPredCreate.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_void_p)]
        lib.MXPredCreateFromFile.restype = ctypes.c_int
        lib.MXPredCreateFromFile.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
        lib.MXPredSetInput.restype = ctypes.c_int
        lib.MXPredSetInput.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.MXPredForward.restype = ctypes.c_int
        lib.MXPredForward.argtypes = [ctypes.c_void_p]
        lib.MXPredGetOutputShape.restype = ctypes.c_int
        lib.MXPredGetOutputShape.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int)]
        lib.MXPredGetOutput.restype = ctypes.c_int
        lib.MXPredGetOutput.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64]
        lib.MXPredFree.argtypes = [ctypes.c_void_p]
        _PRED_LIB = lib
    except Exception as e:  # degrade gracefully, like the recordio engine
        warnings.warn("native predict runtime unavailable: %s" % e)
        _PRED_LIB = None
    return _PRED_LIB
