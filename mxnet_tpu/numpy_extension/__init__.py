"""``mx.npx``: NumPy-extension operators (reference:
``python/mxnet/numpy_extension/`` -- the neural-network ops that have no
NumPy equivalent, exposed alongside ``mx.np``)."""
from __future__ import annotations

import numpy as _onp

from ..ndarray import NDArray
from ..ndarray import ndarray as _nd_mod
from ..numpy import _view, _views, array as np_array
from ..ops.registry import get_op

_np_active = False


def set_np(shape=True, array=True):
    """Enable numpy semantics globally (reference: ``npx.set_np``).
    Gluon blocks then return ``mx.np.ndarray`` views
    (``gluon/block.py :: Block.__call__``)."""
    global _np_active
    _np_active = bool(array)


def reset_np():
    global _np_active
    _np_active = False


def is_np_array():
    return _np_active


def is_np_shape():
    return _np_active


def _call(opname, tensor_args, **params):
    return _views(_nd_mod.invoke(get_op(opname), tensor_args, params))


def relu(data):
    return _call("relu", [data])


def sigmoid(data):
    return _call("sigmoid", [data])


def softmax(data, axis=-1):
    return _call("softmax", [data], axis=axis)


def log_softmax(data, axis=-1):
    return _call("log_softmax", [data], axis=axis)


def activation(data, act_type="relu"):
    return _call("Activation", [data], act_type=act_type)


def fully_connected(x, weight, bias=None, num_hidden=0, no_bias=False,
                    flatten=True):
    return _call("FullyConnected", [x, weight, bias],
                 num_hidden=num_hidden,
                 no_bias=no_bias or bias is None, flatten=flatten)


def convolution(data, weight, bias=None, kernel=(1, 1), stride=(1, 1),
                pad=(0, 0), num_filter=0, no_bias=False, **kwargs):
    return _call("Convolution", [data, weight, bias], kernel=kernel,
                 stride=stride, pad=pad, num_filter=num_filter,
                 no_bias=no_bias or bias is None, **kwargs)


def pooling(data, kernel=(2, 2), stride=None, pad=(0, 0),
            pool_type="max", **kwargs):
    return _call("Pooling", [data], kernel=kernel,
                 stride=stride or kernel, pad=pad, pool_type=pool_type,
                 **kwargs)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, **kwargs):
    return _views(_nd_mod.invoke(
        get_op("BatchNorm"), [x, gamma, beta, running_mean, running_var],
        dict(eps=eps, momentum=momentum, **kwargs)))


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _call("LayerNorm", [data, gamma, beta], axis=axis, eps=eps)


def embedding(data, weight, input_dim=0, output_dim=0):
    return _call("Embedding", [data, weight], input_dim=input_dim,
                 output_dim=output_dim)


def one_hot(data, depth, on_value=1.0, off_value=0.0):
    return _call("one_hot", [data], depth=depth, on_value=on_value,
                 off_value=off_value)


def pick(data, index, axis=-1, keepdims=False):
    return _call("pick", [data, index], axis=axis, keepdims=keepdims)


def topk(data, k=1, axis=-1, ret_typ="indices"):
    return _call("topk", [data], k=k, axis=axis, ret_typ=ret_typ)


def reshape_like(lhs, rhs):
    return _call("reshape_like", [lhs, rhs])


def save(file, arr_dict):
    """Reference: ``npx.save`` -- same .params container as mx.nd."""
    from ..ndarray import save as nd_save
    nd_save(file, arr_dict)


def load(file):
    from ..ndarray import load as nd_load
    return {k: _view(v) for k, v in nd_load(file).items()}


def seed(s):
    from .. import random as rnd
    rnd.seed(s)


def waitall():
    from ..ndarray import waitall as w
    w()
