"""BaseModule: the generic train/eval loop contract (reference:
``python/mxnet/module/base_module.py :: BaseModule``).

The intermediate-level legacy API: ``bind -> init_params ->
init_optimizer -> fit/score/predict``.  Subclasses implement the
computation (``forward/backward/update``); this class owns the epoch
loop, metric bookkeeping, and callback plumbing.
"""
from __future__ import annotations

import logging
import time

from .. import io as mxio
from .. import metric as metric_mod
from ..base import MXNetError
from ..initializer import Uniform
from ..model import BatchEndParam


def _as_metric(m):
    if isinstance(m, metric_mod.EvalMetric):
        return m
    return metric_mod.create(m)


def _check_input_names(symbol, names, typename, throw):
    args = set(symbol.list_arguments())
    for name in names:
        if name not in args:
            msg = "input %s %r is not an argument of the symbol " \
                  "(arguments: %s)" % (typename, name,
                                       sorted(args)[:20])
            if throw:
                raise MXNetError(msg)
            logging.warning(msg)


class BaseModule:
    """Reference: ``BaseModule`` -- defines ``fit``/``score``/``predict``
    over the subclass's forward/backward/update primitives."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ------------------------------------------------------------------
    # High-level interface
    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None,
              reset=True, epoch=0):
        """Evaluate over ``eval_data`` (reference: ``BaseModule.score``)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric,
                                      locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        if score_end_callback is not None:
            param = BatchEndParam(epoch=epoch, nbatch=0,
                                  eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        """Forward over a DataIter, collecting outputs (reference:
        ``BaseModule.predict``)."""
        from .. import ndarray as nd
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            outs = self.get_outputs()
            if eval_batch.pad:
                outs = [o[:o.shape[0] - eval_batch.pad] for o in outs]
            output_list.append(outs)
        if not output_list:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.concat(*[b[i] for b in output_list], dim=0)
                      for i in range(num_outputs)]
            return merged[0] if num_outputs == 1 else merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="device", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The canonical legacy training loop (reference:
        ``BaseModule.fit``)."""
        assert num_epoch is not None, "please specify num_epoch"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            end_of_batch = False
            data_iter = iter(train_data)
            next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                self.forward_backward(data_batch)
                self.update()
                try:
                    next_data_batch = next(data_iter)
                except StopIteration:
                    end_of_batch = True
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            train_data.reset()

    # ------------------------------------------------------------------
    # Properties / abstract interface
    # ------------------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        raise NotImplementedError()

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="device", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]
