"""Module: the legacy symbolic training API (reference:
``python/mxnet/module/module.py :: Module``).

TPU-native design: instead of the reference's
``DataParallelExecutorGroup`` (one executor per GPU + explicit gradient
copy/reduce), ONE Executor jits the whole graph and XLA/PJRT handles
placement; multi-device data parallelism is the ``mxnet_tpu.parallel``
mesh path, not executor replication.  ``grad_req``/``inputs_need_grad``
semantics match the reference.
"""
from __future__ import annotations

import logging

from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError
from ..initializer import Uniform, InitDesc
from ..io.io import DataDesc
from ..model import load_params, save_checkpoint
from .base_module import BaseModule, _check_input_names


def _normalize_shapes(shapes):
    """Accept DataDesc, (name, shape) tuples, or dicts."""
    if shapes is None:
        return []
    if isinstance(shapes, dict):
        shapes = list(shapes.items())
    out = []
    for s in shapes:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            name, shape = s[0], tuple(s[1])
            out.append(DataDesc(name, shape))
    return out


class Module(BaseModule):
    """Reference: ``Module(symbol, data_names, label_names, context)``."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._context = context

        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        _check_input_names(symbol, self._data_names, "data", True)
        _check_input_names(symbol, self._label_names, "label", False)

        self._exec = None
        self._optimizer = None
        self._updater = None
        self._data_shapes = None
        self._label_shapes = None
        self._inputs_need_grad = False
        self._input_grads = None

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        shapes = {d.name: d.shape for d in self._data_shapes +
                  (self._label_shapes or [])}
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self.output_names, out_shapes))

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write", group2ctx=None):
        """Allocate the executor for the given input shapes (reference:
        ``Module.bind``).  Weight shapes come from graph shape inference
        (`Symbol.infer_shape`)."""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        self._data_shapes = _normalize_shapes(data_shapes)
        self._label_shapes = _normalize_shapes(label_shapes)

        shapes = {d.name: d.shape for d in self._data_shapes}
        shapes.update({d.name: d.shape for d in self._label_shapes})
        if not for_training:
            grad_req = "null"
        req = {}
        for name in self._symbol.list_arguments():
            if name in self._fixed_param_names:
                req[name] = "null"
            elif name in self._label_names:
                req[name] = "null"
            elif name in self._data_names:
                req[name] = grad_req if inputs_need_grad else "null"
            else:
                req[name] = grad_req

        arg_names = self._symbol.list_arguments()
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shapes)
        args = {n: nd.zeros(s, ctx=self._context)
                for n, s in zip(arg_names, arg_shapes)}
        args_grad = {n: nd.zeros(args[n].shape, ctx=self._context)
                     for n in arg_names if req[n] != "null"}
        aux_states = {n: nd.zeros(s, ctx=self._context)
                      for n, s in zip(self._aux_names, aux_shapes)}
        from ..executor import Executor
        self._exec = Executor(self._symbol, self._context, args, args_grad,
                              req, aux_states=aux_states,
                              group2ctx=group2ctx)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            arg_p, aux_p = shared_module.get_params()
            self.set_params(arg_p, aux_p)

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """Reference: ``Module.init_params`` -- explicit dicts win,
        otherwise the Initializer runs with the parameter's InitDesc."""
        assert self.binded, "call bind before init_params"
        if self.params_initialized and not force_init:
            return
        if arg_params is None and hasattr(self, "_preloaded_params"):
            arg_params, preloaded_aux = self._preloaded_params
            aux_params = aux_params or preloaded_aux
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._data = arg_params[name]._data
            elif arg_params is not None and not allow_missing:
                raise MXNetError("missing parameter %r (pass "
                                 "allow_missing=True to initialize it)"
                                 % name)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
        for name, arr in self._exec.aux_dict.items():
            if aux_params is not None and name in aux_params:
                arr._data = aux_params[name]._data
            elif initializer is not None:
                initializer(InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: v.copy() for n, v in self._exec.aux_dict.items()}
        return arg, aux

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def init_optimizer(self, kvstore="device", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Reference: ``Module.init_optimizer``.  TPU note: there is one
        logical parameter copy per process (XLA owns placement), so the
        single-process update-on-kvstore split collapses -- the Updater
        runs directly.  A ``dist*`` kvstore engages the multi-process
        path: rank 0's parameters are broadcast (the reference's
        kv.init + pull) and every ``update()`` allreduces gradients
        across workers before the local update, exactly the
        ``Module.fit(..., kvstore='dist_sync')`` workflow."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            # reference behavior: Module normalizes gradients by the
            # batch size via optimizer.rescale_grad
            if "rescale_grad" not in optimizer_params and self._data_shapes:
                optimizer_params["rescale_grad"] = \
                    1.0 / self._data_shapes[0].shape[0]
            optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                   **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)
        self._kvstore = None
        if isinstance(kvstore, str):
            if kvstore.startswith("dist"):
                from .. import kvstore as kvs
                self._kvstore = kvs.create(kvstore)
        elif kvstore is not None:
            self._kvstore = kvstore
        if self._kvstore is not None and \
                getattr(self._kvstore, "_is_dist", False):
            # rank 0's parameters + aux go to every worker (reference
            # kv.init + pull), however the kvstore was supplied -- ONE
            # bucketed collective for the whole set, not one per tensor
            from ..distributed import host_broadcast_bucketed, world
            if world()[0] > 1:
                arrs = [self._exec.arg_dict[name]
                        for name in self._param_names
                        if name in self._exec.arg_dict]
                arrs += [arr for _name, arr in
                         sorted(self._exec.aux_dict.items())]
                out = host_broadcast_bucketed([a._data for a in arrs],
                                              root=0)
                for a, v in zip(arrs, out):
                    a._data = v
        self.optimizer_initialized = True
        if getattr(self, "_preloaded_states", None):
            self.load_optimizer_states(self._preloaded_states)
            self._preloaded_states = None

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        def to_ctx(arr):
            # batches arrive on the iterator's (host) context; executors
            # run where the module was bound (reference: executor-group
            # slice-and-copy semantics)
            if self._context is not None and arr.context != self._context:
                return arr.as_in_context(self._context)
            return arr

        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = to_ctx(arr)
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = to_ctx(arr)
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Apply one optimizer step to every parameter (reference:
        ``Module.update``); with a dist kvstore, gradients allreduce
        across workers first (``kvstore_dist.h :: Push/Pull``)."""
        assert self.optimizer_initialized
        kv = getattr(self, "_kvstore", None)
        for i, name in enumerate(self._param_names):
            if name not in self._exec.grad_dict:
                continue
            grad = self._exec.grad_dict[name]
            if kv is not None and getattr(kv, "_is_dist", False):
                kv.pushpull(i, grad, out=grad)
            self._updater(i, grad, self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self._inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # ------------------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Reference: ``Module.save_checkpoint`` -- ``prefix-symbol.json``
        + ``prefix-%04d.params`` (+ ``.states``)."""
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            assert self.optimizer_initialized
            from ..checkpoint.core import atomic_write_bytes
            atomic_write_bytes("%s-%04d.states" % (prefix, epoch),
                               self._updater.get_states(dump_optimizer=True))

    def load_optimizer_states(self, fname):
        """Reference: ``Module.load_optimizer_states``."""
        assert self.optimizer_initialized
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())
        self._optimizer = self._updater.optimizer

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Reference: ``Module.load``.  Parameters apply at
        ``init_params``; optimizer states (if requested) apply at
        ``init_optimizer``."""
        from .. import symbol as sym
        symbol = sym.load("%s-symbol.json" % prefix)
        mod = Module(symbol, **kwargs)
        arg_params, aux_params = load_params(prefix, epoch)
        mod._preloaded_params = (arg_params, aux_params)
        if load_optimizer_states:
            mod._preloaded_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def init_params_from_load(self):
        arg_params, aux_params = getattr(self, "_preloaded_params",
                                         (None, None))
        self.init_params(arg_params=arg_params, aux_params=aux_params)
