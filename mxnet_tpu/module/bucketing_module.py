"""BucketingModule: variable-length training via per-bucket executors
(reference: ``python/mxnet/module/bucketing_module.py``).

TPU-native framing: a bucket is a static shape class; each bucket gets
its own jitted Executor (one XLA program per bucket, compiled once,
cached thereafter) while all buckets share the same parameter arrays --
the same idea as Gluon hybridize's shape-keyed jit cache, surfaced
through the legacy API.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    """Reference: ``BucketingModule(sym_gen, default_bucket_key, ...)``.
    ``sym_gen(bucket_key) -> (symbol, data_names, label_names)``."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, fixed_param_names=None, state_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._init_args = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def bucket_keys(self):
        """Keys with a compiled executor so far (one XLA program per
        shape class)."""
        return sorted(self._buckets)

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        return self._sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        return self._sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    def _gen_module(self, bucket_key):
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names=data_names,
                      label_names=label_names, logger=self.logger,
                      context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        """Bind the default bucket (reference: ``BucketingModule.bind``)."""
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                 force_rebind=False)
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = mod
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Compile-or-reuse the executor for ``bucket_key``, sharing
        parameters with the default bucket (reference:
        ``switch_bucket``)."""
        assert self.binded, "call bind before switch_bucket"
        if bucket_key not in self._buckets:
            mod = self._gen_module(bucket_key)
            mod.bind(data_shapes, label_shapes, self.for_training,
                     force_rebind=False)
            if self.params_initialized:
                arg, aux = self._buckets[
                    self._default_bucket_key].get_params()
                mod.init_params(arg_params=arg, aux_params=aux,
                                allow_missing=False, force_init=True)
                mod.params_initialized = True
            if self._curr_module.optimizer_initialized:
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                # the dist kvstore must follow the optimizer: a bucket
                # updating without it would skip the gradient allreduce
                # and silently diverge the workers
                mod._kvstore = getattr(self._curr_module, "_kvstore",
                                       None)
                mod.optimizer_initialized = True
            self._buckets[bucket_key] = mod
        else:
            mod = self._buckets[bucket_key]
            if self.params_initialized and self._curr_module is not mod:
                arg, aux = self._curr_module.get_params()
                mod.init_params(arg_params=arg, aux_params=aux,
                                force_init=True)
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        assert self.binded
        if self.params_initialized and not force_init:
            return
        self._curr_module.init_params(initializer, arg_params, aux_params,
                                      allow_missing, force_init, allow_extra)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params=None, **kwargs):
        self._curr_module.set_params(arg_params, aux_params, **kwargs)
        self.params_initialized = True

    def init_optimizer(self, kvstore="device", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params, force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        key = getattr(data_batch, "bucket_key", self._curr_bucket_key)
        if key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        assert self.optimizer_initialized
        if not self._curr_module.optimizer_initialized:
            default = self._buckets[self._default_bucket_key]
            self._curr_module._optimizer = default._optimizer
            self._curr_module._updater = default._updater
            self._curr_module._kvstore = getattr(default, "_kvstore",
                                                 None)
            self._curr_module.optimizer_initialized = True
        self._curr_module.update()
        # propagate updated params + aux (BN running stats) back to the
        # default bucket so newly compiled buckets start from the latest
        if self._curr_bucket_key != self._default_bucket_key:
            default = self._buckets[self._default_bucket_key]
            for name in self._curr_module._param_names:
                default._exec.arg_dict[name]._data = \
                    self._curr_module._exec.arg_dict[name]._data
            for name, arr in self._curr_module._exec.aux_dict.items():
                if name in default._exec.aux_dict:
                    default._exec.aux_dict[name]._data = arr._data

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)
