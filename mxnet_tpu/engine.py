"""Engine shim.

The reference's dependency engine (``src/engine/threaded_engine.cc``)
schedules every mutation as an async op over versioned vars.  On TPU,
XLA/PJRT's async runtime already provides dataflow ordering and async
dispatch (SURVEY.md §1), so this module keeps only the *control surface*:
sync points, the bulk controls (wired to the bulked-eager region queue
in ``ndarray/bulk.py``), and the naive-engine debug switch (eager
blocking mode for race isolation).
"""
from __future__ import annotations

import contextlib
import os

from .ndarray import bulk as _bulk
from .ndarray.ndarray import waitall  # re-export  # noqa: F401

_blocking = os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


def set_bulk_size(size):
    """Reference: ``mx.engine.set_bulk_size`` -- sets the max eager ops
    per bulked region (the capacity-flush threshold of the bulked-eager
    queue, ``ndarray/bulk.py``); returns the previous size.  ``size <=
    1`` disables bulking, flushing any pending region first."""
    return _bulk.set_bulk_size(size)


@contextlib.contextmanager
def bulk(size):
    """Bulk scope (reference: ``with mx.engine.bulk(size):``): eager ops
    inside queue into regions of up to ``size`` ops that replay as one
    jitted program; the pending region executes at scope exit (the
    reference's bulk-segment boundary), then the previous bulk size is
    restored."""
    prev = _bulk.set_bulk_size(size)
    try:
        yield
    finally:
        _bulk.flush()
        _bulk.set_bulk_size(prev)


def is_blocking():
    return _blocking
