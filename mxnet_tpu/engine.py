"""Engine shim.

The reference's dependency engine (``src/engine/threaded_engine.cc``)
schedules every mutation as an async op over versioned vars.  On TPU,
XLA/PJRT's async runtime already provides dataflow ordering and async
dispatch (SURVEY.md §1), so this module keeps only the *control surface*:
sync points, a bulk scope (no-op: XLA fuses), and the naive-engine debug
switch (eager blocking mode for race isolation).
"""
from __future__ import annotations

import contextlib
import os

from .ndarray.ndarray import waitall  # re-export  # noqa: F401

_blocking = os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


def set_bulk_size(size):
    """Reference: ``mx.engine.set_bulk_size`` -- XLA fusion makes bulking
    automatic; retained for API parity."""
    return size


@contextlib.contextmanager
def bulk(size):
    yield


def is_blocking():
    return _blocking
