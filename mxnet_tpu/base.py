"""Base utilities: errors, registries, naming.

TPU-native re-design of the reference's ``python/mxnet/base.py`` and
dmlc-core error machinery (reference: ``python/mxnet/base.py :: check_call,
MXNetError``; ``3rdparty/dmlc-core/include/dmlc/logging.h``).  There is no C
ABI boundary here: the compute substrate is JAX/XLA, so errors are native
Python exceptions raised at op-call or sync points.
"""
from __future__ import annotations

import re


class MXNetError(RuntimeError):
    """Framework error type (reference: ``base.py :: MXNetError``).

    Raised for shape/type inference failures, bad op arguments, and errors
    surfaced at synchronization points (``asnumpy``, ``wait_to_read``) --
    mirroring the reference's async error propagation contract
    (``src/engine/threaded_engine.cc :: OnCompleteStatic``).
    """


def check_call(ret):
    """Compatibility no-op: there is no flat C ABI in the TPU build."""
    return ret


_CAMEL_RE1 = re.compile(r"(.)([A-Z][a-z]+)")
_CAMEL_RE2 = re.compile(r"([a-z0-9])([A-Z])")


def camel_to_snake(name: str) -> str:
    s = _CAMEL_RE1.sub(r"\1_\2", name)
    return _CAMEL_RE2.sub(r"\1_\2", s).lower()


class _NameManager:
    """Auto-naming scope (reference: ``python/mxnet/name.py :: NameManager``)."""

    _current = None

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%d" % (hint, idx)

    @classmethod
    def current(cls):
        if cls._current is None:
            cls._current = _NameManager()
        return cls._current

    def __enter__(self):
        self._old = _NameManager._current
        _NameManager._current = self
        return self

    def __exit__(self, *args):
        _NameManager._current = self._old


def build_param_doc(params) -> str:
    """Render an op's typed parameter list as a numpydoc section.

    TPU-native analog of the reference's dmlc::Parameter ``__DOC__``
    generation (``3rdparty/dmlc-core/include/dmlc/parameter.h``): the op
    registry is self-describing and Python signatures/docstrings are
    generated from it at import time.
    """
    lines = ["Parameters", "----------"]
    for p in params:
        lines.append("%s : %s, optional, default=%r" % (p.name, p.type_str, p.default)
                     if p.has_default else "%s : %s, required" % (p.name, p.type_str))
        if p.doc:
            lines.append("    " + p.doc)
    return "\n".join(lines)
