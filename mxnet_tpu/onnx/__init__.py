"""ONNX interchange (reference: ``python/mxnet/onnx`` /
``mx.contrib.onnx``).

The environment this framework is developed in has no ``onnx`` package
(zero egress), so the converter is **API-gated**: the public surface and
the op mapping table exist, and `export_model`/`import_model` raise a
clear error until `onnx` is importable.  The graph side is ready -- our
``-symbol.json`` DAG maps 1:1 onto an ONNX GraphProto (op nodes +
initializers from the ``.params`` file).
"""
from __future__ import annotations

from ..base import MXNetError

# op-name mapping our graphs would emit (subset; extended on demand)
MX2ONNX_OP = {
    "FullyConnected": "Gemm",
    "Convolution": "Conv",
    "Activation": None,           # dispatched on act_type
    "relu": "Relu",
    "sigmoid": "Sigmoid",
    "tanh": "Tanh",
    "softmax": "Softmax",
    "Pooling": None,              # MaxPool/AveragePool on pool_type
    "BatchNorm": "BatchNormalization",
    "Flatten": "Flatten",
    "Concat": "Concat",
    "elemwise_add": "Add",
    "elemwise_mul": "Mul",
    "Dropout": "Dropout",
    "Reshape": "Reshape",
    "transpose": "Transpose",
    "dot": "MatMul",
}


def _require_onnx():
    try:
        import onnx  # noqa: F401
        return onnx
    except ImportError as e:
        raise MXNetError(
            "the `onnx` package is not available in this environment; "
            "mx.onnx export/import is gated until it is installed") from e


def export_model(sym, params, in_shapes=None, in_types=None,
                 onnx_file_path="model.onnx", **kwargs):
    """Reference: ``mx.onnx.export_model``.

    NOT IMPLEMENTED: conversion needs the onnx package to build and
    validate GraphProtos, which this environment cannot install; the
    call raises either way (with the missing-package cause chained when
    that is the blocker)."""
    _require_onnx()
    raise MXNetError("mx.onnx.export_model conversion is not implemented "
                     "yet (the graph mapping table MX2ONNX_OP is the "
                     "starting point)")


def import_model(model_file):
    """Reference: ``mx.contrib.onnx.import_model``.  NOT IMPLEMENTED --
    see export_model."""
    _require_onnx()
    raise MXNetError("mx.onnx.import_model conversion is not implemented "
                     "yet")
