"""ONNX interchange (reference: ``python/mxnet/contrib/onnx`` ::
``export_model`` / ``import_model``, ``mx2onnx/_op_translations.py``,
``onnx2mx/_import_helper.py``).

The environment has no ``onnx`` package (zero egress), so serialization
goes through a self-contained protobuf wire-format implementation
(``wire.py``) -- ONNX files are plain protobuf, and the subset the
format uses (varints + length-delimited messages) is stable.  Exported
files follow IR version 8 / default opset 13 and are readable by any
standard ONNX parser; ``import_model`` reads files produced by this
exporter and by stock exporters (it accepts raw_data and typed tensor
payloads, packed and unpacked repeated fields).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from . import wire

__all__ = ["export_model", "import_model", "MX2ONNX_OP", "ONNX2MX_OP",
           "get_model_metadata"]


def _attr(node, key, default=None):
    from ..symbol.symbol import _parse_attr_value
    if key not in node.attrs:
        return default
    return _parse_attr_value(node.attrs[key])


def _ints(v, n=None):
    if v is None:
        return None
    if isinstance(v, (int, np.integer)):
        v = (int(v),) * (n or 1)
    return [int(x) for x in v]


# ----------------------------------------------------------------------
# Export: Symbol graph -> ModelProto bytes
# ----------------------------------------------------------------------

# simple 1:1 renames (everything else has a converter function below)
MX2ONNX_OP = {
    "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh", "exp": "Exp",
    "log": "Log", "sqrt": "Sqrt", "abs": "Abs", "negative": "Neg",
    "erf": "Erf", "floor": "Floor", "ceil": "Ceil", "identity": "Identity",
    "elemwise_add": "Add", "elemwise_sub": "Sub", "elemwise_mul": "Mul",
    "elemwise_div": "Div", "broadcast_add": "Add", "broadcast_sub": "Sub",
    "broadcast_mul": "Mul", "broadcast_div": "Div",
    "broadcast_power": "Pow", "broadcast_maximum": "Max",
    "broadcast_minimum": "Min", "matmul": "MatMul",
    "add_n": "Sum", "Flatten": "Flatten",
}


class _Exporter:
    def __init__(self, sym, params, in_shapes, in_types):
        self.sym = sym
        self.params = params
        self.in_shapes = list(in_shapes or [])
        self.in_types = list(in_types or [])
        self.nodes = []          # NodeProto bytes, topo order
        self.initializers = []   # TensorProto bytes
        self.init_names = set()
        self.graph_inputs = []   # ValueInfo bytes
        self.entry_name = {}     # (id(node), out_idx) -> tensor name
        self.counter = 0
        self._ranks = None       # (id(node), out_idx) -> rank or None

    def _internal_ranks(self):
        """Best-effort rank map for every internal output, via partial
        shape inference seeded with the graph-input shapes and the
        (always-known) parameter shapes.  Unknowns map to None."""
        if self._ranks is not None:
            return self._ranks
        self._ranks = {}
        try:
            kwargs = {}
            in_idx = 0
            for n in self.sym._topo():
                if n.op is not None:
                    continue
                if n.name in self.params:
                    kwargs[n.name] = tuple(self.params[n.name].shape)
                else:
                    if in_idx < len(self.in_shapes):
                        kwargs[n.name] = tuple(self.in_shapes[in_idx])
                    in_idx += 1
            internals = self.sym.get_internals()
            _, out_shapes, _ = internals.infer_shape_partial(**kwargs)
            for (node, idx), shp in zip(internals._outputs, out_shapes):
                self._ranks[(id(node), idx)] = \
                    None if shp is None else len(shp)
        except Exception:
            pass
        return self._ranks

    def fresh(self, base):
        self.counter += 1
        return "%s__%d" % (base, self.counter)

    def in_name(self, node, i):
        src, idx = node.inputs[i]
        return self.entry_name[(id(src), idx)]

    def add_node(self, op_type, inputs, outputs, name, attrs=None):
        self.nodes.append(wire.make_node(op_type, inputs, outputs,
                                         name=name, attrs=attrs))

    def add_init(self, name, arr):
        if name not in self.init_names:
            self.initializers.append(wire.make_tensor(name, arr))
            self.init_names.add(name)

    # -- per-op converters --------------------------------------------

    def conv(self, node):
        layout = str(node.attrs.get("layout", "NCHW") or "NCHW")
        if layout and layout[-1] == "C":
            raise MXNetError("onnx export: channels-last Convolution is "
                             "not representable; use NCHW layout")
        kernel = _ints(_attr(node, "kernel", ()))
        nsp = len(kernel)
        attrs = {"kernel_shape": kernel,
                 "group": int(_attr(node, "num_group", 1) or 1)}
        stride = _ints(_attr(node, "stride", None), nsp)
        dilate = _ints(_attr(node, "dilate", None), nsp)
        pad = _ints(_attr(node, "pad", None), nsp)
        if stride:
            attrs["strides"] = stride
        if dilate:
            attrs["dilations"] = dilate
        if pad:
            attrs["pads"] = pad + pad
        op = "Conv" if node.op == "Convolution" else "ConvTranspose"
        if op == "ConvTranspose":
            adj = _ints(_attr(node, "adj", None), nsp)
            if adj and any(adj):
                attrs["output_padding"] = adj
        ins = [self.in_name(node, i) for i in range(len(node.inputs))]
        self.add_node(op, ins, [node.name], node.name, attrs)

    def fully_connected(self, node):
        flatten = _attr(node, "flatten", True)
        no_bias = bool(_attr(node, "no_bias", False))
        x = self.in_name(node, 0)
        if flatten:
            flat = self.fresh(node.name + "_flat")
            self.add_node("Flatten", [x], [flat], flat, {"axis": 1})
            x = flat
        ins = [x, self.in_name(node, 1)]
        if not no_bias and len(node.inputs) > 2:
            ins.append(self.in_name(node, 2))
        self.add_node("Gemm", ins, [node.name], node.name,
                      {"alpha": 1.0, "beta": 1.0, "transB": 1})

    def activation(self, node):
        act = str(node.attrs.get("act_type", "relu"))
        m = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
        if act not in m:
            raise MXNetError("onnx export: Activation %r unsupported" % act)
        self.add_node(m[act], [self.in_name(node, 0)], [node.name],
                      node.name)

    def leaky_relu(self, node):
        act = str(node.attrs.get("act_type", "leaky"))
        slope = float(_attr(node, "slope", 0.25))
        if act == "leaky":
            self.add_node("LeakyRelu", [self.in_name(node, 0)],
                          [node.name], node.name, {"alpha": slope})
        elif act == "elu":
            self.add_node("Elu", [self.in_name(node, 0)], [node.name],
                          node.name, {"alpha": slope})
        elif act == "selu":
            self.add_node("Selu", [self.in_name(node, 0)], [node.name],
                          node.name)
        else:
            raise MXNetError("onnx export: LeakyReLU %r unsupported" % act)

    def batch_norm(self, node):
        if int(_attr(node, "axis", 1)) != 1:
            raise MXNetError("onnx export: BatchNorm axis must be 1 "
                             "(channels-first)")
        attrs = {"epsilon": float(_attr(node, "eps", 1e-5)),
                 "momentum": float(_attr(node, "momentum", 0.9))}
        ins = [self.in_name(node, i) for i in range(5)]
        if _attr(node, "fix_gamma", True):
            # the op ignores gamma when fix_gamma: bake ones so ONNX
            # semantics match (reference mx2onnx does the same)
            gname = ins[1]
            if gname in self.params:
                shape = np.asarray(self.params[gname]).shape
                ones_name = self.fresh(gname + "_fixed")
                self.add_init(ones_name, np.ones(shape, np.float32))
                ins[1] = ones_name
        self.add_node("BatchNormalization", ins, [node.name], node.name,
                      attrs)

    def pooling(self, node):
        layout = str(node.attrs.get("layout", "NCHW") or "NCHW")
        if layout and layout[-1] == "C":
            raise MXNetError("onnx export: channels-last Pooling is not "
                             "representable; use NCHW layout")
        pool_type = str(node.attrs.get("pool_type", "max"))
        global_pool = bool(_attr(node, "global_pool", False))
        x = self.in_name(node, 0)
        if global_pool:
            op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(
                pool_type)
            if op is None:
                raise MXNetError("onnx export: global %s pool unsupported"
                                 % pool_type)
            self.add_node(op, [x], [node.name], node.name)
            return
        kernel = _ints(_attr(node, "kernel", ()))
        nsp = len(kernel)
        attrs = {"kernel_shape": kernel}
        stride = _ints(_attr(node, "stride", None), nsp)
        pad = _ints(_attr(node, "pad", None), nsp)
        if stride:
            attrs["strides"] = stride
        if pad:
            attrs["pads"] = pad + pad
        if str(node.attrs.get("pooling_convention", "valid")) == "full":
            attrs["ceil_mode"] = 1
        if pool_type == "avg":
            attrs["count_include_pad"] = \
                1 if _attr(node, "count_include_pad", True) else 0
            op = "AveragePool"
        elif pool_type == "max":
            op = "MaxPool"
        else:
            raise MXNetError("onnx export: pool_type %r unsupported"
                             % pool_type)
        self.add_node(op, [x], [node.name], node.name, attrs)

    def reshape(self, node):
        shape = _ints(_attr(node, "shape", ()))
        if any(s in (-2, -3, -4) for s in shape):
            raise MXNetError("onnx export: Reshape codes -2/-3/-4 are not "
                             "representable in ONNX")
        sname = self.fresh(node.name + "_shape")
        self.add_init(sname, np.asarray(shape, np.int64))
        self.add_node("Reshape", [self.in_name(node, 0), sname],
                      [node.name], node.name)

    def scalar_op(self, node):
        scalar = float(_attr(node, "scalar", 0.0))
        cname = self.fresh(node.name + "_scalar")
        self.add_init(cname, np.asarray(scalar, np.float32))
        x = self.in_name(node, 0)
        op_map = {"_plus_scalar": ("Add", [x, cname]),
                  "_minus_scalar": ("Sub", [x, cname]),
                  "_rminus_scalar": ("Sub", [cname, x]),
                  "_mul_scalar": ("Mul", [x, cname]),
                  "_div_scalar": ("Div", [x, cname]),
                  "_rdiv_scalar": ("Div", [cname, x]),
                  "_power_scalar": ("Pow", [x, cname]),
                  "_rpower_scalar": ("Pow", [cname, x])}
        op, ins = op_map[node.op]
        self.add_node(op, ins, [node.name], node.name)

    def softmax(self, node):
        self.add_node("Softmax", [self.in_name(node, 0)], [node.name],
                      node.name, {"axis": int(_attr(node, "axis", -1))})

    def transpose(self, node):
        axes = _ints(_attr(node, "axes", ()))
        attrs = {"perm": axes} if axes else None
        self.add_node("Transpose", [self.in_name(node, 0)], [node.name],
                      node.name, attrs)

    def concat(self, node):
        ins = [self.in_name(node, i) for i in range(len(node.inputs))]
        axis = int(_attr(node, "dim", _attr(node, "axis", 1)))
        self.add_node("Concat", ins, [node.name], node.name,
                      {"axis": axis})

    def dropout(self, node):
        # inference export: Dropout is identity
        self.add_node("Identity", [self.in_name(node, 0)], [node.name],
                      node.name)

    def clip(self, node):
        lo = self.fresh(node.name + "_min")
        hi = self.fresh(node.name + "_max")
        self.add_init(lo, np.asarray(_attr(node, "a_min", 0.0), np.float32))
        self.add_init(hi, np.asarray(_attr(node, "a_max", 0.0), np.float32))
        self.add_node("Clip", [self.in_name(node, 0), lo, hi],
                      [node.name], node.name)

    def embedding(self, node):
        # Gather(weight, indices): note the operand order swap
        self.add_node("Gather", [self.in_name(node, 1),
                                 self.in_name(node, 0)],
                      [node.name], node.name, {"axis": 0})

    def expand_dims(self, node):
        ax = self.fresh(node.name + "_axes")
        self.add_init(ax, np.asarray([int(_attr(node, "axis", 0))],
                                     np.int64))
        self.add_node("Unsqueeze", [self.in_name(node, 0), ax],
                      [node.name], node.name)

    def dot(self, node):
        # ONNX MatMul has numpy semantics and no transpose attrs; only
        # the untransposed form maps losslessly (mx dot's ND behavior is
        # tensordot(axes=1), which MatMul matches for rank <= 2; rank is
        # unknown at export time, so transposes are rejected, not
        # silently dropped)
        if _attr(node, "transpose_a", False) or \
                _attr(node, "transpose_b", False):
            raise MXNetError("onnx export: %s with transpose_a/b is not "
                             "representable as MatMul" % node.op)
        if node.op == "dot":
            # mx dot is tensordot(axes=1); MatMul's numpy semantics agree
            # only while the RHS has rank <= 2 (a rank>2 RHS makes MatMul
            # broadcast batch dims instead of chaining them).  Verify via
            # shape inference; reject rather than export silently wrong.
            rb = self._internal_ranks().get(
                (id(node.inputs[1][0]), node.inputs[1][1]))
            if rb is None or rb > 2:
                raise MXNetError(
                    "onnx export: dot with a rank-%s second operand is "
                    "not representable as MatMul (mx dot chains trailing "
                    "dims, MatMul broadcasts batch dims); pass in_shapes "
                    "proving rank <= 2 or rewrite with batch_dot"
                    % ("unknown" if rb is None else rb))
        self.add_node("MatMul", [self.in_name(node, 0),
                                 self.in_name(node, 1)],
                      [node.name], node.name)

    def simple(self, node):
        op = MX2ONNX_OP[node.op]
        ins = [self.in_name(node, i) for i in range(len(node.inputs))]
        attrs = {"axis": 1} if op == "Flatten" else None
        self.add_node(op, ins, [node.name], node.name, attrs)

    CONVERTERS = {
        "Convolution": conv, "Deconvolution": conv,
        "FullyConnected": fully_connected, "Activation": activation,
        "LeakyReLU": leaky_relu, "BatchNorm": batch_norm,
        "Pooling": pooling, "Reshape": reshape, "softmax": softmax,
        "transpose": transpose, "Concat": concat, "Dropout": dropout,
        "clip": clip, "Embedding": embedding, "expand_dims": expand_dims,
        "dot": dot, "batch_dot": dot,
        "_plus_scalar": scalar_op, "_minus_scalar": scalar_op,
        "_rminus_scalar": scalar_op, "_mul_scalar": scalar_op,
        "_div_scalar": scalar_op, "_rdiv_scalar": scalar_op,
        "_power_scalar": scalar_op, "_rpower_scalar": scalar_op,
    }

    def run(self):
        from ..ndarray import NDArray
        sym = self.sym
        in_idx = 0
        for node in sym._topo():
            if node.op is None:
                name = node.name
                self.entry_name[(id(node), 0)] = name
                if name in self.params:
                    arr = self.params[name]
                    arr = arr.asnumpy() if isinstance(arr, NDArray) \
                        else np.asarray(arr)
                    self.add_init(name, arr)
                else:
                    shape = self.in_shapes[in_idx] \
                        if in_idx < len(self.in_shapes) else ()
                    dt = wire.DT_FLOAT
                    if in_idx < len(self.in_types):
                        dt = wire._NP2DT.get(
                            np.dtype(self.in_types[in_idx]), wire.DT_FLOAT)
                    in_idx += 1
                    self.graph_inputs.append(
                        wire.make_value_info(name, dt, shape))
                continue
            conv_fn = self.CONVERTERS.get(node.op)
            self.entry_name[(id(node), 0)] = node.name
            for i in range(1, node.num_outputs):
                self.entry_name[(id(node), i)] = "%s_out%d" % (node.name, i)
            if conv_fn is not None:
                conv_fn(self, node)
            elif node.op in MX2ONNX_OP:
                self.simple(node)
            else:
                raise MXNetError("onnx export: no converter for op %r"
                                 % node.op)
        outputs = []
        for onode, idx in sym._outputs:
            outputs.append(wire.make_value_info(
                self.entry_name[(id(onode), idx)], wire.DT_FLOAT, ()))
        graph = wire.make_graph(self.nodes, "mxnet_tpu_graph",
                                self.graph_inputs, outputs,
                                self.initializers)
        return wire.make_model(graph)


def export_model(sym, params, in_shapes=None, in_types=None,
                 onnx_file_path="model.onnx", **kwargs):
    """Export a Symbol graph (or saved model prefix) to an ONNX file.

    Reference: ``mx.onnx.export_model(sym, params, in_shapes, in_types,
    onnx_file_path)``.  ``sym`` is a Symbol or a ``*-symbol.json`` path;
    ``params`` a dict (``arg:``/``aux:`` prefixes accepted) or a
    ``.params`` path.  Returns ``onnx_file_path``.
    """
    from .. import ndarray as nd
    from ..symbol import symbol as sym_mod
    if isinstance(sym, str):
        sym = sym_mod.load(sym)
    if isinstance(params, str):
        params = nd.load(params)
    flat = {}
    for k, v in (params or {}).items():
        if ":" in k:
            k = k.split(":", 1)[1]
        flat[k] = v
    model = _Exporter(sym, flat, in_shapes, in_types).run()
    from ..checkpoint.core import atomic_write_bytes
    atomic_write_bytes(onnx_file_path, model)
    return onnx_file_path


def get_model_metadata(model_file):
    """Input/output names+shapes of an ONNX file (reference:
    ``mx.contrib.onnx.get_model_metadata``)."""
    with open(model_file, "rb") as f:
        model = wire.parse_model(f.read())
    g = model["graph"]
    inits = {n for n, _ in g["initializers"]}
    return {
        "input_tensor_data": [(n, tuple(s)) for n, _t, s in g["inputs"]
                              if n not in inits],
        "output_tensor_data": [(n, tuple(s)) for n, _t, s in g["outputs"]],
    }


# ----------------------------------------------------------------------
# Import: ModelProto -> (Symbol, arg_params, aux_params)
# ----------------------------------------------------------------------

ONNX2MX_OP = {
    "Relu": ("Activation", {"act_type": "relu"}),
    "Sigmoid": ("Activation", {"act_type": "sigmoid"}),
    "Tanh": ("Activation", {"act_type": "tanh"}),
    "Softplus": ("Activation", {"act_type": "softrelu"}),
    "Softsign": ("Activation", {"act_type": "softsign"}),
    "Exp": ("exp", {}), "Log": ("log", {}), "Sqrt": ("sqrt", {}),
    "Abs": ("abs", {}), "Neg": ("negative", {}), "Erf": ("erf", {}),
    "Floor": ("floor", {}), "Ceil": ("ceil", {}),
    "Add": ("broadcast_add", {}), "Sub": ("broadcast_sub", {}),
    "Mul": ("broadcast_mul", {}), "Div": ("broadcast_div", {}),
    "Pow": ("broadcast_power", {}), "MatMul": ("matmul", {}),
    "Sum": ("add_n", {}), "Identity": ("identity", {}),
}


def _onnx_pads(attrs, nsp, kernel=None, strides=None, dilations=None):
    """Symmetric per-axis pads from ``pads`` or ``auto_pad``.

    Third-party exporters (tf2onnx, some torch eras) emit ``auto_pad``
    instead of explicit ``pads``; SAME_* resolves without the input
    shape only when the padded total is even per axis, which holds for
    the ubiquitous odd-kernel/stride-1 convs -- anything else is
    rejected loudly rather than imported wrong.
    """
    auto = attrs.get("auto_pad", "NOTSET")
    if isinstance(auto, bytes):
        auto = auto.decode()
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        if attrs.get("pads"):
            raise MXNetError("onnx import: both pads and auto_pad set")
        kernel = list(kernel or [])
        strides = list(strides or [1] * nsp)
        dilations = list(dilations or [1] * nsp)
        out = []
        for k, s, d in zip(kernel, strides, dilations):
            if s != 1:
                raise MXNetError(
                    "onnx import: auto_pad=%s with stride %d needs the "
                    "input shape; re-export with explicit pads" % (auto, s))
            total = d * (k - 1)
            if total % 2:
                raise MXNetError(
                    "onnx import: auto_pad=%s is asymmetric for "
                    "even-kernel axis (kernel %d)" % (auto, k))
            out.append(total // 2)
        return out
    pads = attrs.get("pads")
    if not pads:
        return [0] * nsp
    begin, end = pads[:nsp], pads[nsp:]
    if list(begin) != list(end):
        raise MXNetError("onnx import: asymmetric pads %r unsupported"
                         % (pads,))
    return list(begin)


class _Importer:
    def __init__(self, model):
        self.graph = model["graph"]
        self.inits = {n: a for n, a in self.graph["initializers"]}
        self.env = {}          # tensor name -> Symbol
        self.used_params = set()
        self.unsupported_outputs = {}  # extra output name -> op_type

    def sym_of(self, name):
        from ..symbol import symbol as S
        if name in self.unsupported_outputs:
            raise MXNetError(
                "onnx import: output %r of a %s node is consumed, but "
                "only the primary output is supported"
                % (name, self.unsupported_outputs[name]))
        if name not in self.env:
            self.env[name] = S.var(name)
        if name in self.inits:
            self.used_params.add(name)
        return self.env[name]

    def const_of(self, name):
        """Initializer consumed as a structural constant (shapes, axes)."""
        if name not in self.inits:
            raise MXNetError("onnx import: %r must be an initializer"
                             % name)
        return self.inits[name]

    def run(self):
        from ..symbol.symbol import Group, _make_node
        g = self.graph
        for node in g["nodes"]:
            op = node["op_type"]
            a = node["attrs"]
            ins = node["input"]
            out = node["output"][0]
            nm = node["name"] or out

            if op in ("Conv", "ConvTranspose"):
                w = self.inits.get(ins[1])
                # kernel_shape is optional in the spec: third-party
                # graphs routinely rely on the weight's trailing dims
                kernel = a.get("kernel_shape") or list(w.shape[2:])
                nsp = len(kernel)
                stride = a.get("strides", [1] * nsp)
                dilate = a.get("dilations", [1] * nsp)
                params = {"kernel": tuple(kernel),
                          "stride": tuple(stride),
                          "dilate": tuple(dilate),
                          "pad": tuple(_onnx_pads(a, nsp, kernel=kernel,
                                                  strides=stride,
                                                  dilations=dilate)),
                          "num_group": int(a.get("group", 1)),
                          "no_bias": len(ins) < 3}
                if op == "Conv":
                    params["num_filter"] = int(w.shape[0]) \
                        if w is not None else 0
                    mxop = "Convolution"
                else:
                    grp = params["num_group"]
                    params["num_filter"] = int(w.shape[1]) * grp \
                        if w is not None else 0
                    params["adj"] = tuple(a.get("output_padding",
                                                [0] * nsp))
                    mxop = "Deconvolution"
                syms = [self.sym_of(i) for i in ins]
                res = _make_node(mxop, syms, params, name=nm)
            elif op == "Gemm":
                alpha, beta = a.get("alpha", 1.0), a.get("beta", 1.0)
                if (alpha, beta) != (1.0, 1.0):
                    raise MXNetError("onnx import: Gemm alpha/beta != 1")
                if a.get("transA", 0):
                    raise MXNetError("onnx import: Gemm transA unsupported")
                w_name = ins[1]
                if not a.get("transB", 0):
                    if w_name not in self.inits:
                        raise MXNetError("onnx import: Gemm transB=0 needs "
                                         "an initializer weight")
                    # keep the original untouched (it may feed other
                    # consumers); this Gemm binds a transposed copy
                    t_name = w_name + "_transposed"
                    if t_name not in self.inits:
                        self.inits[t_name] = \
                            np.ascontiguousarray(self.inits[w_name].T)
                    w_name = t_name
                    ins = [ins[0], t_name] + list(ins[2:])
                w = self.inits.get(w_name)
                params = {"num_hidden": int(w.shape[0]) if w is not None
                          else 0, "no_bias": len(ins) < 3,
                          "flatten": False}
                syms = [self.sym_of(i) for i in ins]
                res = _make_node("FullyConnected", syms, params, name=nm)
            elif op == "BatchNormalization":
                params = {"eps": float(a.get("epsilon", 1e-5)),
                          "momentum": float(a.get("momentum", 0.9)),
                          "fix_gamma": False}
                syms = [self.sym_of(i) for i in ins[:3]]
                # running stats are aux states in the mx graph
                from ..attribute import AttrScope
                with AttrScope(__aux__="1"):
                    syms += [self.sym_of(i) for i in ins[3:5]]
                res = _make_node("BatchNorm", syms, params, name=nm)
            elif op in ("MaxPool", "AveragePool"):
                kernel = a["kernel_shape"]
                nsp = len(kernel)
                stride = a.get("strides", [1] * nsp)
                params = {"kernel": tuple(kernel),
                          "stride": tuple(stride),
                          "pad": tuple(_onnx_pads(a, nsp, kernel=kernel,
                                                  strides=stride)),
                          "pool_type": "max" if op == "MaxPool" else "avg",
                          "pooling_convention":
                          "full" if a.get("ceil_mode") else "valid"}
                if op == "AveragePool":
                    # the ONNX spec default is 0 (exclude padding) --
                    # our exporter always writes the attr explicitly,
                    # so honoring the spec default only changes
                    # third-party graphs, where it is what they meant
                    params["count_include_pad"] = \
                        bool(a.get("count_include_pad", 0))
                res = _make_node("Pooling", [self.sym_of(ins[0])], params,
                                 name=nm)
            elif op in ("GlobalMaxPool", "GlobalAveragePool"):
                params = {"global_pool": True,
                          "pool_type":
                          "max" if op == "GlobalMaxPool" else "avg"}
                res = _make_node("Pooling", [self.sym_of(ins[0])], params,
                                 name=nm)
            elif op == "Flatten":
                if int(a.get("axis", 1)) != 1:
                    raise MXNetError("onnx import: Flatten axis != 1")
                res = _make_node("Flatten", [self.sym_of(ins[0])], {},
                                 name=nm)
            elif op == "Constant":
                # a Constant node IS an initializer wearing node syntax
                # (the dominant third-party idiom for Reshape shapes)
                val = a.get("value")
                if val is None and "value_float" in a:
                    val = np.asarray(a["value_float"], np.float32)
                if val is None and "value_int" in a:
                    val = np.asarray(a["value_int"], np.int64)
                if val is None and "value_ints" in a:
                    val = np.asarray(a["value_ints"], np.int64)
                if val is None:
                    raise MXNetError("onnx import: Constant node %r has "
                                     "no supported value attr" % nm)
                self.inits[out] = np.asarray(val)
                continue
            elif op == "Reshape":
                if len(ins) > 1:
                    shape = [int(x) for x in self.const_of(ins[1])]
                else:
                    # opset<5 idiom (still emitted by some exporters):
                    # the target shape rides as an attribute
                    shape = [int(x) for x in a.get("shape", ())]
                    if not shape:
                        raise MXNetError("onnx import: Reshape without "
                                         "shape input or attr")
                res = _make_node("Reshape", [self.sym_of(ins[0])],
                                 {"shape": tuple(shape)}, name=nm)
            elif op == "Transpose":
                params = {}
                if "perm" in a:
                    params["axes"] = tuple(a["perm"])
                res = _make_node("transpose", [self.sym_of(ins[0])],
                                 params, name=nm)
            elif op == "Concat":
                res = _make_node("Concat",
                                 [self.sym_of(i) for i in ins],
                                 {"dim": int(a.get("axis", 1)),
                                  "num_args": len(ins)}, name=nm)
            elif op == "Softmax":
                res = _make_node("softmax", [self.sym_of(ins[0])],
                                 {"axis": int(a.get("axis", -1))}, name=nm)
            elif op == "LeakyRelu":
                res = _make_node("LeakyReLU", [self.sym_of(ins[0])],
                                 {"act_type": "leaky",
                                  "slope": float(a.get("alpha", 0.01))},
                                 name=nm)
            elif op == "Elu":
                res = _make_node("LeakyReLU", [self.sym_of(ins[0])],
                                 {"act_type": "elu",
                                  "slope": float(a.get("alpha", 1.0))},
                                 name=nm)
            elif op == "Selu":
                res = _make_node("LeakyReLU", [self.sym_of(ins[0])],
                                 {"act_type": "selu"}, name=nm)
            elif op == "Clip":
                if len(ins) >= 3:
                    lo = float(self.const_of(ins[1]))
                    hi = float(self.const_of(ins[2]))
                else:
                    lo = float(a.get("min", -np.inf))
                    hi = float(a.get("max", np.inf))
                res = _make_node("clip", [self.sym_of(ins[0])],
                                 {"a_min": lo, "a_max": hi}, name=nm)
            elif op == "Gather":
                if int(a.get("axis", 0)) != 0:
                    raise MXNetError("onnx import: Gather axis != 0")
                res = _make_node("Embedding",
                                 [self.sym_of(ins[1]),
                                  self.sym_of(ins[0])], {}, name=nm)
            elif op == "Unsqueeze":
                axes = a.get("axes")
                if axes is None:
                    axes = [int(x) for x in self.const_of(ins[1])]
                if any(ax < 0 for ax in axes) and len(axes) > 1:
                    raise MXNetError("onnx import: negative multi-axis "
                                     "Unsqueeze")
                res = self.sym_of(ins[0])
                # multi-axis unsqueeze = chained expand_dims, ascending
                # so earlier insertions don't shift later axes
                for i, ax in enumerate(sorted(int(x) for x in axes)):
                    res = _make_node("expand_dims", [res],
                                     {"axis": ax},
                                     name=nm if i == len(axes) - 1
                                     else "%s_ax%d" % (nm, ax))
            elif op == "Squeeze":
                axes = a.get("axes")
                if axes is None and len(ins) > 1:
                    axes = [int(x) for x in self.const_of(ins[1])]
                params = {} if axes is None \
                    else {"axis": tuple(int(x) for x in axes)}
                res = _make_node("squeeze", [self.sym_of(ins[0])],
                                 params, name=nm)
            elif op == "ReduceMean":
                # ResNet-style third-party graphs spell global average
                # pooling as ReduceMean over the spatial axes
                axes = a.get("axes")
                if axes is None and len(ins) > 1:
                    axes = [int(x) for x in self.const_of(ins[1])]
                if list(axes or []) != [2, 3]:
                    raise MXNetError(
                        "onnx import: ReduceMean only supported over "
                        "spatial axes [2, 3] (got %r)" % (axes,))
                pooled = _make_node("Pooling", [self.sym_of(ins[0])],
                                    {"global_pool": True,
                                     "pool_type": "avg"},
                                    name=nm + "_gap"
                                    if not a.get("keepdims", 1) else nm)
                if a.get("keepdims", 1):
                    res = pooled
                else:
                    res = _make_node("Flatten", [pooled], {}, name=nm)
            elif op == "Dropout":
                res = self.sym_of(ins[0])
            elif op in ONNX2MX_OP:
                mxop, params = ONNX2MX_OP[op]
                res = _make_node(mxop, [self.sym_of(i) for i in ins],
                                 dict(params), name=nm)
            else:
                raise MXNetError("onnx import: no converter for op %r"
                                 % op)
            self.env[out] = res[0] if len(res) > 1 else res
            for extra in node["output"][1:]:
                # declared-but-unsupported secondary outputs (Dropout
                # mask, BN training stats): error on use, not silently
                # alias the primary output
                if extra:
                    self.unsupported_outputs[extra] = op

        outs = [self.sym_of(n) for n, _t, _s in self.graph["outputs"]]
        sym = outs[0] if len(outs) == 1 else Group(outs)

        from .. import ndarray as nd
        arg_params, aux_params = {}, {}
        arg_names = set(sym.list_arguments())
        aux_names = set(sym.list_auxiliary_states())
        for name, arr in self.inits.items():
            if name not in self.used_params:
                continue  # structural constant (shape/axes), consumed
            t = nd.array(np.ascontiguousarray(arr))
            if name in aux_names:
                aux_params[name] = t
            elif name in arg_names:
                arg_params[name] = t
        return sym, arg_params, aux_params


def import_model(model_file):
    """Import an ONNX file -> ``(sym, arg_params, aux_params)``
    (reference: ``mx.contrib.onnx.import_model``)."""
    with open(model_file, "rb") as f:
        model = wire.parse_model(f.read())
    return _Importer(model).run()
