"""Self-contained ONNX protobuf wire format (no ``onnx``/``protobuf``
dependency).

Reference: ``python/mxnet/onnx`` serializes through the onnx pip
package; this environment has no network, so the stable protobuf wire
format (varint tags + length-delimited submessages -- the only parts
ONNX uses) is implemented directly.  Field numbers follow onnx.proto3
(IR version 8 era); readers accept both packed and unpacked repeated
scalars, writers emit ONNX's own conventions (packed numeric tensor
payloads in ``raw_data``).

Wire types: 0 = varint, 1 = 64-bit, 2 = length-delimited, 5 = 32-bit.
"""
from __future__ import annotations

import struct

import numpy as np

from ..base import MXNetError

# -- primitives --------------------------------------------------------


def _uvarint(n):
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_uvarint(buf, pos):
    shift = 0
    val = 0
    while True:
        if pos >= len(buf):
            raise MXNetError("onnx: truncated varint")
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > 70:
            raise MXNetError("onnx: varint too long")


def _svarint(n):
    # int64 fields are encoded two's-complement as uint64
    return _uvarint(n & (1 << 64) - 1)


def _to_signed(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def field_varint(num, val):
    return _uvarint(num << 3 | 0) + _svarint(int(val))


def field_bytes(num, payload):
    if isinstance(payload, str):
        payload = payload.encode("utf-8")
    return _uvarint(num << 3 | 2) + _uvarint(len(payload)) + payload


def field_float(num, val):
    return _uvarint(num << 3 | 5) + struct.pack("<f", float(val))


def parse_message(buf):
    """Parse one protobuf message into {field_number: [(wiretype, value)]}.
    Length-delimited values stay as bytes (caller recurses as needed)."""
    fields = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_uvarint(buf, pos)
        num, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _read_uvarint(buf, pos)
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _read_uvarint(buf, pos)
            val = buf[pos:pos + ln]
            if len(val) != ln:
                raise MXNetError("onnx: truncated length-delimited field")
            pos += ln
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise MXNetError("onnx: unsupported wire type %d" % wt)
        fields.setdefault(num, []).append((wt, val))
    return fields


def get_ints(fields, num):
    """Repeated int64: accepts unpacked varints and packed blobs."""
    out = []
    for wt, v in fields.get(num, []):
        if wt == 0:
            out.append(_to_signed(v))
        elif wt == 2:
            pos = 0
            while pos < len(v):
                x, pos = _read_uvarint(v, pos)
                out.append(_to_signed(x))
    return out


def get_int(fields, num, default=0):
    vals = get_ints(fields, num)
    return vals[-1] if vals else default


def get_floats(fields, num):
    out = []
    for wt, v in fields.get(num, []):
        if wt == 5:
            out.append(struct.unpack("<f", v)[0])
        elif wt == 2:
            out.extend(struct.unpack("<%df" % (len(v) // 4), v))
    return out


def get_bytes(fields, num, default=b""):
    vals = [v for wt, v in fields.get(num, []) if wt == 2]
    return vals[-1] if vals else default


def get_str(fields, num, default=""):
    b = get_bytes(fields, num, None)
    return b.decode("utf-8") if b is not None else default


def get_all_bytes(fields, num):
    return [v for wt, v in fields.get(num, []) if wt == 2]


# -- TensorProto -------------------------------------------------------

# onnx TensorProto.DataType
DT_FLOAT, DT_UINT8, DT_INT8, DT_UINT16, DT_INT16, DT_INT32, DT_INT64 = \
    1, 2, 3, 4, 5, 6, 7
DT_BOOL, DT_FLOAT16, DT_DOUBLE, DT_UINT32, DT_UINT64, DT_BFLOAT16 = \
    9, 10, 11, 12, 13, 16

_NP2DT = {
    np.dtype(np.float32): DT_FLOAT, np.dtype(np.uint8): DT_UINT8,
    np.dtype(np.int8): DT_INT8, np.dtype(np.uint16): DT_UINT16,
    np.dtype(np.int16): DT_INT16, np.dtype(np.int32): DT_INT32,
    np.dtype(np.int64): DT_INT64, np.dtype(np.bool_): DT_BOOL,
    np.dtype(np.float16): DT_FLOAT16, np.dtype(np.float64): DT_DOUBLE,
    np.dtype(np.uint32): DT_UINT32, np.dtype(np.uint64): DT_UINT64,
}
_DT2NP = {v: k for k, v in _NP2DT.items()}


def make_tensor(name, arr):
    """TensorProto from a numpy array (payload in raw_data, little-endian,
    as onnx's own exporters emit)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype.name == "bfloat16":
        dt = DT_BFLOAT16
    elif arr.dtype in _NP2DT:
        dt = _NP2DT[arr.dtype]
    else:  # anything exotic: store as fp32
        arr = np.ascontiguousarray(arr.astype(np.float32))
        dt = DT_FLOAT
    raw = arr.tobytes()
    out = b""
    for d in arr.shape:
        out += field_varint(1, d)            # dims
    out += field_varint(2, dt)               # data_type
    out += field_bytes(8, name)              # name
    out += field_bytes(9, raw)               # raw_data
    return out


def parse_tensor(buf):
    """-> (name, numpy array)."""
    f = parse_message(buf)
    dims = get_ints(f, 1)
    dt = get_int(f, 2, DT_FLOAT)
    name = get_str(f, 8)
    raw = get_bytes(f, 9, None)
    if dt == DT_BFLOAT16:
        import ml_dtypes
        np_dt = np.dtype(ml_dtypes.bfloat16)
    elif dt in _DT2NP:
        np_dt = _DT2NP[dt]
    else:
        raise MXNetError("onnx: unsupported tensor data_type %d" % dt)
    if raw is not None:
        arr = np.frombuffer(raw, dtype=np_dt).reshape(dims).copy()
    else:
        # typed repeated fields (float_data=4, int32_data=5, int64_data=7)
        if dt == DT_FLOAT:
            arr = np.asarray(get_floats(f, 4), np.float32).reshape(dims)
        elif dt == DT_INT64:
            arr = np.asarray(get_ints(f, 7), np.int64).reshape(dims)
        elif dt in (DT_INT32, DT_INT16, DT_INT8, DT_UINT16, DT_UINT8,
                    DT_BOOL):
            arr = np.asarray(get_ints(f, 5), np_dt).reshape(dims)
        else:
            raise MXNetError("onnx: tensor %r has no payload" % name)
    return name, arr


# -- AttributeProto ----------------------------------------------------

AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR, AT_GRAPH = 1, 2, 3, 4, 5
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8


def make_attr(name, value):
    out = field_bytes(1, name)
    if isinstance(value, bool):
        out += field_varint(3, int(value)) + field_varint(20, AT_INT)
    elif isinstance(value, (int, np.integer)):
        out += field_varint(3, int(value)) + field_varint(20, AT_INT)
    elif isinstance(value, (float, np.floating)):
        out += field_float(2, value) + field_varint(20, AT_FLOAT)
    elif isinstance(value, (str, bytes)):
        out += field_bytes(4, value) + field_varint(20, AT_STRING)
    elif isinstance(value, np.ndarray):
        out += field_bytes(5, make_tensor("", value)) \
            + field_varint(20, AT_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], (float, np.floating)):
            for v in value:
                out += field_float(7, v)
            out += field_varint(20, AT_FLOATS)
        elif value and isinstance(value[0], (str, bytes)):
            for v in value:
                out += field_bytes(9, v)
            out += field_varint(20, AT_STRINGS)
        else:
            for v in value:
                out += field_varint(8, int(v))
            out += field_varint(20, AT_INTS)
    else:
        raise MXNetError("onnx: unsupported attribute value %r" % (value,))
    return out


def parse_attr(buf):
    """-> (name, python value)."""
    f = parse_message(buf)
    name = get_str(f, 1)
    at = get_int(f, 20, 0)
    if at == AT_FLOAT:
        return name, get_floats(f, 2)[-1]
    if at == AT_INT:
        return name, get_int(f, 3)
    if at == AT_STRING:
        return name, get_bytes(f, 4).decode("utf-8")
    if at == AT_TENSOR:
        return name, parse_tensor(get_bytes(f, 5))[1]
    if at == AT_FLOATS:
        return name, get_floats(f, 7)
    if at == AT_INTS:
        return name, get_ints(f, 8)
    if at == AT_STRINGS:
        return name, [b.decode("utf-8") for b in get_all_bytes(f, 9)]
    # tolerate untyped attrs: guess by populated field
    if 3 in f:
        return name, get_int(f, 3)
    if 8 in f:
        return name, get_ints(f, 8)
    raise MXNetError("onnx: attribute %r has unsupported type %d"
                     % (name, at))


# -- Node / ValueInfo / Graph / Model ---------------------------------


def make_node(op_type, inputs, outputs, name="", attrs=None, domain=""):
    out = b""
    for i in inputs:
        out += field_bytes(1, i)
    for o in outputs:
        out += field_bytes(2, o)
    if name:
        out += field_bytes(3, name)
    out += field_bytes(4, op_type)
    for k, v in (attrs or {}).items():
        out += field_bytes(5, make_attr(k, v))
    if domain:
        out += field_bytes(7, domain)
    return out


def parse_node(buf):
    f = parse_message(buf)
    return {
        "input": [b.decode("utf-8") for b in get_all_bytes(f, 1)],
        "output": [b.decode("utf-8") for b in get_all_bytes(f, 2)],
        "name": get_str(f, 3),
        "op_type": get_str(f, 4),
        "attrs": dict(parse_attr(a) for a in get_all_bytes(f, 5)),
    }


def make_value_info(name, elem_type, shape):
    dims = b""
    for d in shape:
        if isinstance(d, (int, np.integer)) and d >= 0:
            dims += field_bytes(1, field_varint(1, d))     # dim_value
        else:
            dims += field_bytes(1, field_bytes(2, str(d)))  # dim_param
    tensor_type = field_varint(1, elem_type) + field_bytes(2, dims)
    type_proto = field_bytes(1, tensor_type)
    return field_bytes(1, name) + field_bytes(2, type_proto)


def parse_value_info(buf):
    f = parse_message(buf)
    name = get_str(f, 1)
    shape = []
    elem_type = DT_FLOAT
    tp = get_bytes(f, 2, None)
    if tp is not None:
        tpf = parse_message(tp)
        tt = get_bytes(tpf, 1, None)
        if tt is not None:
            ttf = parse_message(tt)
            elem_type = get_int(ttf, 1, DT_FLOAT)
            shp = get_bytes(ttf, 2, None)
            if shp is not None:
                for dim_buf in get_all_bytes(parse_message(shp), 1):
                    df = parse_message(dim_buf)
                    if 1 in df:
                        shape.append(get_int(df, 1))
                    else:
                        shape.append(get_str(df, 2) or 0)
    return name, elem_type, shape


def make_graph(nodes, name, inputs, outputs, initializers):
    out = b""
    for n in nodes:
        out += field_bytes(1, n)
    out += field_bytes(2, name)
    for t in initializers:
        out += field_bytes(5, t)
    for vi in inputs:
        out += field_bytes(11, vi)
    for vi in outputs:
        out += field_bytes(12, vi)
    return out


def parse_graph(buf):
    f = parse_message(buf)
    return {
        "nodes": [parse_node(b) for b in get_all_bytes(f, 1)],
        "name": get_str(f, 2),
        "initializers": [parse_tensor(b) for b in get_all_bytes(f, 5)],
        "inputs": [parse_value_info(b) for b in get_all_bytes(f, 11)],
        "outputs": [parse_value_info(b) for b in get_all_bytes(f, 12)],
    }


def make_model(graph, ir_version=8, opset=13, producer="mxnet_tpu",
               producer_version="1.0", domain=""):
    opset_id = field_bytes(1, domain) + field_varint(2, opset)
    out = field_varint(1, ir_version)
    out += field_bytes(8, opset_id)      # opset_import (field 8)
    out += field_bytes(2, producer)
    out += field_bytes(3, producer_version)
    out += field_bytes(7, graph)         # graph (field 7)
    return out


def parse_model(buf):
    f = parse_message(buf)
    graph_buf = get_bytes(f, 7, None)
    if graph_buf is None:
        raise MXNetError("onnx: ModelProto has no graph")
    opsets = {}
    for b in get_all_bytes(f, 8):
        of = parse_message(b)
        opsets[get_str(of, 1)] = get_int(of, 2)
    return {
        "ir_version": get_int(f, 1),
        "producer": get_str(f, 2),
        "opset": opsets,
        "graph": parse_graph(graph_buf),
    }
