"""Async checkpoint writing: snapshot on the loop thread, commit on a
background thread (ISSUE 3 layer 2).

A synchronous save serializes the whole state through the filesystem
while the accelerators idle.  The TPU-native split is: at the loop
boundary, drain the async queue (``nd.waitall``) and ``device_get`` the
params/optimizer state to host memory -- cheap relative to the write --
then hand the *host* snapshot to a writer thread that serializes,
fsyncs, and atomically commits while the next steps run.

Contract (mirrors what production checkpointing libraries converged
on):

- **at-most-one-in-flight** -- a new save first drains the previous
  one, so checkpoints land in order and host memory holds at most one
  extra copy of the state;
- **transient weather is retried** -- a failed background write (a
  full disk blip, an NFS hiccup, an injected chaos fault) retries up
  to ``MXNET_TPU_CKPT_WRITE_RETRIES`` times with exponential backoff
  (``MXNET_TPU_CKPT_RETRY_BACKOFF_S`` doubling per attempt); retries
  are counted (``checkpoint.write_retries``);
- **errors are never swallowed** -- a write that fails every attempt
  is surfaced through the ``checkpoint.write_failed`` telemetry event
  (+ ``checkpoint.write_failures`` counter) AND stored for re-raise at
  the *next* ``save()``/``wait_until_finished()``, the spots a
  training loop actually checks;
- ``wait_until_finished()`` is the durability barrier: after it
  returns, the bytes are committed.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import chaos as _chaos
from .. import sync as _sync
from .. import telemetry as _telemetry
from ..base import MXNetError

__all__ = ["AsyncWriter", "snapshot_items"]

# Test seam: when set to a threading.Event, the writer thread blocks on
# it before serializing -- how tests/test_checkpoint.py proves the
# training loop advances while the bytes are NOT yet on disk.
_TEST_WRITE_GATE = None


def _to_host(value):
    """One array -> host numpy, without a round-trip through the device
    (np.asarray on a jax.Array is a device_get)."""
    from .. import ndarray as nd
    if isinstance(value, nd.NDArray):
        return value.asnumpy()
    return np.asarray(value)


def snapshot_items(items):
    """Copy a save's payload to host memory at a consistent loop
    boundary: ``waitall`` first (so no in-flight update can tear the
    snapshot), then ``device_get`` every array.  Returns
    ``{name: (kind, payload)}`` with payloads safe to hand to another
    thread."""
    from .. import ndarray as nd
    nd.waitall()
    snapshot = {}
    for name, value in items.items():
        if isinstance(value, (bytes, bytearray, memoryview)):
            snapshot[name] = ("bin", bytes(value))
        elif isinstance(value, dict):
            snapshot[name] = ("params",
                              {k: _to_host(v) for k, v in value.items()})
        else:
            raise MXNetError(
                "checkpoint item %r must be a dict of arrays or bytes, "
                "got %s" % (name, type(value).__name__))
    return snapshot


class AsyncWriter:
    """Background committer with the at-most-one-in-flight contract."""

    def __init__(self, retries=None, backoff_s=None):
        from .. import env as _env
        self._thread = None
        self._error = None
        self._lock = _sync.Lock(name="checkpoint.async_writer")
        self._retries = int(retries if retries is not None
                            else _env.get("MXNET_TPU_CKPT_WRITE_RETRIES"))
        self._backoff_s = float(
            backoff_s if backoff_s is not None
            else _env.get("MXNET_TPU_CKPT_RETRY_BACKOFF_S"))

    # -- error propagation --------------------------------------------
    def check(self):
        """Re-raise (once) an error from a completed background save."""
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    # -- lifecycle -----------------------------------------------------
    def submit(self, fn, step=None):
        """Run ``fn()`` on the writer thread.  Drains the previous save
        first (recording the drain as ``checkpoint.async_wait`` -- if
        this timer rivals the step time, saves are too frequent for the
        I/O), and re-raises any prior writer error."""
        t0 = time.perf_counter()
        self.wait_until_finished()
        waited = time.perf_counter() - t0
        if _telemetry._ENABLED:
            _telemetry.hooks.checkpoint_wait(waited, step=step)

        def _run():
            gate = _TEST_WRITE_GATE
            if gate is not None:
                gate.wait()
            attempts = self._retries + 1
            for attempt in range(1, attempts + 1):
                try:
                    _chaos.fail_point("checkpoint.async_write",
                                      step=step, attempt=attempt)
                    fn()
                except BaseException as e:  # noqa: B036 -- cross threads
                    if attempt < attempts:
                        # transient weather: back off and retry; the
                        # staged dir is re-created from scratch so a
                        # partial attempt can't poison the next one
                        if _telemetry._ENABLED:
                            _telemetry.hooks.checkpoint_retry(
                                attempt, str(e), step=step)
                        time.sleep(self._backoff_s
                                   * (2 ** (attempt - 1)))
                        continue
                    # exhausted: surface loudly (telemetry event) AND
                    # store for the next save()/wait() to re-raise --
                    # never a swallowed thread exception
                    if _telemetry._ENABLED:
                        _telemetry.hooks.checkpoint_write_failed(
                            attempts, str(e), step=step)
                    with self._lock:
                        self._error = e
                else:
                    if attempt > 1:
                        _chaos.survived("checkpoint.async_write",
                                        "retry")
                    return

        self._thread = threading.Thread(
            target=_run, name="mxnet_tpu-ckpt-writer", daemon=True)
        self._thread.start()
        return waited

    def wait_until_finished(self):
        """Join the in-flight save (if any) and surface its error."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        self.check()

    @property
    def in_flight(self):
        t = self._thread
        return t is not None and t.is_alive()
