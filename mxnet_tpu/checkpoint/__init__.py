"""``mxnet_tpu.checkpoint``: async, sharded, managed checkpoints with
atomic commit, integrity verification, and retention (ISSUE 3).

The one subsystem every save/restore path goes through -- the way
``mx.analysis`` unified static checks and ``mx.telemetry`` unified
metrics.  Three layers (docs/checkpointing.md):

- :mod:`~mxnet_tpu.checkpoint.core` -- tmp+fsync+rename atomic file
  commits, step-numbered checkpoint directories with a
  checksum-carrying manifest committed LAST, and a
  :class:`CheckpointManager` with corruption-tolerant discovery and
  retention;
- :mod:`~mxnet_tpu.checkpoint.async_writer` -- host snapshot at the
  loop boundary, serialize/commit on a background thread,
  at-most-one-in-flight, errors re-raised at the next save/wait;
- :mod:`~mxnet_tpu.checkpoint.sharded` -- multi-process runs write
  per-process shard files, barrier, process 0 commits the merged
  manifest; restore reassembles and reshards to the *current* mesh.

Rebased onto this subsystem: ``mx.preemption`` (SIGTERM checkpoints,
now checksum-verified on resume), ``gluon.Trainer.save_states``,
``KVStore.save_optimizer_states``, ``mx.model.save_checkpoint`` /
``Module.save_checkpoint``, and ``mx.callback`` checkpoints.

Env knobs: ``MXNET_TPU_CKPT_ASYNC`` (background writes),
``MXNET_TPU_CKPT_MAX_TO_KEEP`` (retention).
"""
from .core import (Checkpoint, CheckpointError, CheckpointManager,
                   atomic_write_bytes, commit, file_digest,
                   load_manifest, sweep_stale_tmps, verify_files,
                   FORMAT_VERSION, MANIFEST_NAME)
from .async_writer import AsyncWriter, snapshot_items
from . import core
from . import async_writer
from . import sharded

__all__ = [
    "Checkpoint", "CheckpointError", "CheckpointManager", "AsyncWriter",
    "atomic_write_bytes", "commit", "file_digest", "load_manifest",
    "snapshot_items", "sweep_stale_tmps", "verify_files",
    "FORMAT_VERSION", "MANIFEST_NAME",
    "core", "async_writer", "sharded",
]
