"""Atomic, manifest-verified, step-numbered checkpoints (ISSUE 3).

The paper's failure story is checkpoint/restart (SURVEY §5); on a TPU
fleet the failure is *preemption*, and a production run needs exactly
one answer to "where is the newest checkpoint that actually loads".
Before this subsystem the package had five ad-hoc save paths with five
different torn-write behaviors; they all now route through here.

Two layers in this module:

**File commits** -- :func:`commit` writes through a ``<path>.<pid>.tmp``
staging file, fsyncs, then renames (``os.replace``) into place, so a
SIGKILL at any instant leaves either the old file or the new file,
never a truncated hybrid.  Every commit also sweeps stale temps left by
previously killed writers (:func:`sweep_stale_tmps`).

**Managed step directories** -- :class:`CheckpointManager` owns a root
directory of ``step_<N>/`` checkpoints.  A save stages every file in
``step_<N>.<pid>.tmp/``, fsyncs, writes ``manifest.json`` (per-file
byte sizes + CRC32 checksums, process topology, step, user metadata)
LAST, then renames the whole directory into place.  Discovery is
corruption-tolerant: a step whose manifest is missing/invalid or whose
checksums mismatch is skipped with a warning and the previous good step
wins -- a half-written checkpoint can cost one step of progress, never
the job.  Retention (``max_to_keep`` / ``keep_every_n_steps``) and
async writing (``checkpoint/async_writer.py``) hang off the manager;
multi-process sharded layouts live in ``checkpoint/sharded.py``.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import time
import warnings
import zlib

from .. import chaos as _chaos
from .. import obs as _obs
from .. import telemetry as _telemetry
from ..base import MXNetError

__all__ = [
    "CheckpointError", "CheckpointManager", "Checkpoint",
    "commit", "atomic_write_bytes", "sweep_stale_tmps",
    "file_digest", "load_manifest", "verify_files",
    "MANIFEST_NAME", "FORMAT_VERSION",
]

MANIFEST_NAME = "manifest.json"
FORMAT_VERSION = 1
_STEP_RE = re.compile(r"^step_(\d{8})$")
_TMP_RE = re.compile(r"\.(\d+)\.tmp$")
_DIGEST_CHUNK = 1 << 20


class CheckpointError(MXNetError):
    """A checkpoint failed to commit or verify."""


# ----------------------------------------------------------------------
# file commits
# ----------------------------------------------------------------------

def _fsync_dir(path):
    """Durably record a rename/create in its directory (best-effort:
    some filesystems refuse O_RDONLY dir fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _fsync_and_digest(path):
    """fsync ``path`` and return ``(nbytes, crc32)`` in one pass."""
    crc = 0
    nbytes = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_DIGEST_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            nbytes += len(chunk)
        os.fsync(f.fileno())
    return nbytes, crc & 0xFFFFFFFF


def file_digest(path):
    """``(nbytes, crc32)`` of a file (no fsync; verification reads)."""
    crc = 0
    nbytes = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_DIGEST_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            nbytes += len(chunk)
    return nbytes, crc & 0xFFFFFFFF


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    return True


def sweep_stale_tmps(dirpath, prefix=None):
    """Remove ``*.<pid>.tmp`` files/dirs whose writer process is dead.

    A save killed between ``write_fn(tmp)`` and ``os.replace`` strands
    its temp forever (satellite: the pre-subsystem paths leaked these).
    Called at manager init and by every :func:`commit`.  Temps of LIVE
    pids (including our own in-flight async writer) are left alone.
    Returns the paths removed.
    """
    removed = []
    try:
        entries = os.listdir(dirpath)
    except OSError:
        return removed
    for name in entries:
        m = _TMP_RE.search(name)
        if m is None:
            continue
        if prefix is not None and not name.startswith(prefix):
            continue
        pid = int(m.group(1))
        if pid == os.getpid() or _pid_alive(pid):
            continue
        path = os.path.join(dirpath, name)
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed


def commit(path, write_fn):
    """Atomically publish one file: ``write_fn(tmp)`` -> fsync ->
    ``os.replace(tmp, path)``.  Returns ``(nbytes, crc32)`` of the
    committed bytes, so callers can manifest what they wrote.

    On any failure the temp is removed and the previous ``path`` (if
    any) is untouched -- a crashed or raising writer can never leave a
    truncated file where a loadable one used to be.
    """
    path = os.fspath(path)
    tmp = "%s.%d.tmp" % (path, os.getpid())
    try:
        write_fn(tmp)
        nbytes, crc = _fsync_and_digest(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    parent = os.path.dirname(path) or "."
    _fsync_dir(parent)
    sweep_stale_tmps(parent, prefix=os.path.basename(path))
    return nbytes, crc


def atomic_write_bytes(path, data):
    """Atomically replace ``path`` with ``data`` (bytes).  The shared
    helper behind every "write one state blob" site (Trainer.save_states,
    KVStore.save_optimizer_states, Module's ``.states`` files)."""
    def _write(tmp):
        with open(tmp, "wb") as f:
            f.write(data)
    return commit(path, _write)


# ----------------------------------------------------------------------
# manifests
# ----------------------------------------------------------------------

def load_manifest(dirpath):
    """Parse ``manifest.json`` of a step dir; raises CheckpointError if
    missing or invalid."""
    mpath = os.path.join(dirpath, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except OSError as e:
        raise CheckpointError("no manifest in %s: %s" % (dirpath, e)) from e
    except ValueError as e:
        raise CheckpointError("invalid manifest in %s: %s"
                              % (dirpath, e)) from e
    if not isinstance(manifest, dict) or "files" not in manifest:
        raise CheckpointError("malformed manifest in %s" % dirpath)
    return manifest


def verify_files(dirpath, files):
    """Check every manifest entry against the bytes on disk.  Returns a
    list of problem strings (empty = intact).  ``files`` is the
    manifest's ``{fname: {"bytes": n, "crc32": c, ...}}`` mapping."""
    problems = []
    for fname, entry in files.items():
        fpath = os.path.join(dirpath, fname)
        if not os.path.exists(fpath):
            problems.append("missing file %r" % fname)
            continue
        nbytes, crc = file_digest(fpath)
        if nbytes != entry.get("bytes"):
            problems.append("size mismatch on %r: %d != %d"
                            % (fname, nbytes, entry.get("bytes")))
        elif crc != entry.get("crc32"):
            problems.append("crc32 mismatch on %r" % fname)
    return problems


def _topology():
    from ..distributed import world
    try:
        nprocs, rank = world()
    except Exception:
        nprocs, rank = 1, 0
    return {"num_processes": int(nprocs), "process_id": int(rank)}


# ----------------------------------------------------------------------
# item (de)serialization -- shared with sharded.py
# ----------------------------------------------------------------------
# A checkpoint's payload is a dict of named *items*; each item is either
# a dict of arrays (saved in the .params container format) or raw bytes
# (an opaque state blob, e.g. Trainer.save_states output).

def write_item(dirpath, name, kind, payload):
    """Write one staged item file; returns its manifest entry.  Inside
    staging there is no concurrent reader, so the write is plain -- the
    atomicity boundary is the directory rename."""
    if kind == "params":
        from .. import ndarray as nd
        fname = name + ".params"
        nd.save(os.path.join(dirpath, fname), payload)
    elif kind == "bin":
        fname = name + ".bin"
        with open(os.path.join(dirpath, fname), "wb") as f:
            f.write(payload)
    else:
        raise CheckpointError("unknown item kind %r" % kind)
    nbytes, crc = _fsync_and_digest(os.path.join(dirpath, fname))
    return fname, {"bytes": nbytes, "crc32": crc, "kind": kind,
                   "item": name}


def read_item(dirpath, fname, entry):
    """Load one manifest entry back into its Python value."""
    kind = entry.get("kind", "bin")
    fpath = os.path.join(dirpath, fname)
    if kind == "params":
        from .. import ndarray as nd
        return nd.load(fpath)
    if kind == "bin":
        with open(fpath, "rb") as f:
            return f.read()
    raise CheckpointError("unknown item kind %r in manifest" % kind)


class Checkpoint:
    """What :meth:`CheckpointManager.restore` returns: ``step``, the
    ``items`` dict (name -> dict-of-NDArray or bytes), and the user
    ``metadata`` saved alongside."""

    __slots__ = ("step", "items", "metadata")

    def __init__(self, step, items, metadata):
        self.step = step
        self.items = items
        self.metadata = metadata

    def __repr__(self):
        return "Checkpoint(step=%d, items=%s)" % (self.step,
                                                  sorted(self.items))


# ----------------------------------------------------------------------
# manager
# ----------------------------------------------------------------------

class CheckpointManager:
    """Managed step-numbered checkpoints under one root directory.

    ::

        mgr = mx.checkpoint.CheckpointManager(root, max_to_keep=3)
        mgr.save(step, {"params": net._collect_arrays(),
                        "trainer": trainer.get_states()})
        ...
        ckpt = mgr.restore()          # newest intact step (or None)

    ``items`` values are dicts of arrays (saved as ``.params``) or raw
    ``bytes`` blobs.  Convenience wrappers :meth:`save_training` /
    :meth:`restore_training` handle the (block, trainer) pair directly.

    Options (``None`` defers to the env registry):

    - ``max_to_keep`` (``MXNET_TPU_CKPT_MAX_TO_KEEP``; 0 = unlimited):
      oldest steps beyond this many are deleted after each save.
    - ``keep_every_n_steps``: steps divisible by this are exempt from
      ``max_to_keep`` deletion (sparse long-horizon history).
    - ``async_save`` (``MXNET_TPU_CKPT_ASYNC``): snapshot to host at
      ``save()`` (after a ``waitall`` drain), then serialize/commit on
      a background thread so training overlaps the I/O.  At most one
      save is in flight; a new save drains the previous one first, and
      a writer error re-raises at the next ``save``/``wait``.
    - ``sharded`` (default: auto = multi-process runs): each process
      writes only its addressable shards; see ``checkpoint/sharded.py``.
    - ``quarantine`` (``MXNET_TPU_CKPT_QUARANTINE``, default on): a
      step that fails verification during :meth:`latest_step` discovery
      is renamed ``step_<N>.corrupt`` (and counted in
      ``checkpoint.quarantined``) instead of silently skipped, so
      operators can see rollbacks happened and keep the evidence.
    """

    def __init__(self, root, max_to_keep=None, keep_every_n_steps=None,
                 async_save=None, sharded=None, quarantine=None):
        from .. import env as _env
        self.root = os.fspath(root)
        if max_to_keep is None:
            max_to_keep = _env.get("MXNET_TPU_CKPT_MAX_TO_KEEP") or None
        if max_to_keep is not None and max_to_keep < 1:
            max_to_keep = None
        self.max_to_keep = max_to_keep
        self.keep_every_n_steps = keep_every_n_steps or None
        if quarantine is None:
            quarantine = _env.get("MXNET_TPU_CKPT_QUARANTINE")
        self.quarantine = bool(quarantine)
        if async_save is None:
            async_save = _env.get("MXNET_TPU_CKPT_ASYNC")
        self._sharded = sharded
        self._writer = None
        if async_save:
            from .async_writer import AsyncWriter
            self._writer = AsyncWriter()
        os.makedirs(self.root, exist_ok=True)
        sweep_stale_tmps(self.root)
        if _topology()["process_id"] == 0:
            # a dead multi-rank world's shared staging (ISSUE 15):
            # the sharded layout stages without a pid suffix, so its
            # sweep rides an owner marker instead (sharded.py)
            from . import sharded as _sharded
            _sharded.sweep_shared_staging(self.root)

    # -- layout --------------------------------------------------------
    def step_dir(self, step):
        return os.path.join(self.root, "step_%08d" % int(step))

    def all_steps(self):
        """Every committed step number, ascending (no intactness check:
        use :meth:`latest_step` for 'newest that actually loads')."""
        steps = []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return steps
        for name in entries:
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.root, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def _verify_step(self, step):
        """Manifest of an intact step, or None (with a warning)."""
        dirpath = self.step_dir(step)
        try:
            manifest = load_manifest(dirpath)
            problems = verify_files(dirpath, manifest["files"])
        except CheckpointError as e:
            problems = [str(e)]
            manifest = None
        if problems:
            warnings.warn(
                "checkpoint step %d at %s failed verification (%s); "
                "skipping it" % (step, dirpath, "; ".join(problems)),
                RuntimeWarning, stacklevel=3)
            return None
        return manifest

    def _quarantine_step(self, step):
        """Rename a verification-failed step dir to ``<dir>.corrupt``
        so the rollback is visible to operators (and the torn bytes
        stay available as evidence).  Tolerant of a concurrent writer
        re-saving the step or another process quarantining first;
        rank 0 only under multi-process layouts."""
        if not self.quarantine or _topology()["process_id"] != 0:
            return False
        src = self.step_dir(step)
        dst = src + ".corrupt"
        try:
            if os.path.isdir(dst):
                shutil.rmtree(dst, ignore_errors=True)
            os.replace(src, dst)
        except OSError:
            return False
        if _telemetry._ENABLED:
            _telemetry.hooks.checkpoint_quarantine(step, dst)
        _chaos.survived("checkpoint.commit", "quarantine")
        return True

    def latest_step(self):
        """Newest step that passes manifest + checksum verification, or
        None.  A torn/corrupted newest step falls back to the previous
        good one -- the property the atomic commit protocol exists
        for -- and is quarantined (renamed ``.corrupt``) rather than
        silently skipped, so the rollback is observable."""
        for step in reversed(self.all_steps()):
            if self._verify_step(step) is not None:
                return step
            self._quarantine_step(step)
        return None

    # -- save ----------------------------------------------------------
    def save(self, step, items, metadata=None):
        """Checkpoint ``items`` as ``step``.  Synchronous unless the
        manager was built with ``async_save``; either way the device
        queue is drained and the state snapshotted to host *before*
        this returns, so the training loop may mutate params
        immediately."""
        step = int(step)
        if not isinstance(items, dict) or not items:
            raise CheckpointError("save() needs a non-empty items dict")
        if self._writer is not None:
            self._writer.check()        # re-raise a prior writer error
        from .async_writer import snapshot_items
        t0 = time.perf_counter()
        if self._use_sharded():
            from . import sharded
            nbytes = sharded.save_sharded(self, step, items, metadata)
            self._record_save(step, nbytes, time.perf_counter() - t0,
                              async_save=False)
            return
        snapshot = snapshot_items(items)

        def _write():
            nbytes = self._write_step(step, snapshot, metadata)
            self._apply_retention()
            return nbytes

        if self._writer is not None:
            self._writer.submit(_write, step=step)
            self._record_save(step, None, time.perf_counter() - t0,
                              async_save=True)
        else:
            nbytes = _write()
            self._record_save(step, nbytes, time.perf_counter() - t0,
                              async_save=False)

    def _use_sharded(self):
        if self._sharded is not None:
            return self._sharded
        return _topology()["num_processes"] > 1

    def _record_save(self, step, nbytes, seconds, async_save):
        if _telemetry._ENABLED:
            _telemetry.hooks.checkpoint("save", nbytes=nbytes,
                                        seconds=seconds, step=step,
                                        root=self.root,
                                        async_save=async_save)

    def _write_step(self, step, snapshot, metadata):
        """Serialize a host snapshot into a staged dir and commit it.
        Runs on the writer thread under async saves."""
        _sp = _obs.begin_span("checkpoint.commit", step=step) \
            if _obs._TRACE_ENABLED else None
        try:
            return self._write_step_inner(step, snapshot, metadata)
        finally:
            if _sp is not None:
                _obs.end_span(_sp)

    def _write_step_inner(self, step, snapshot, metadata):
        final = self.step_dir(step)
        staging = "%s.%d.tmp" % (final, os.getpid())
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
        files = {}
        total = 0
        for name, (kind, payload) in sorted(snapshot.items()):
            fname, entry = write_item(staging, name, kind, payload)
            files[fname] = entry
            total += entry["bytes"]
        manifest = {
            "format_version": FORMAT_VERSION,
            "step": step,
            "files": files,
            "topology": _topology(),
            "metadata": metadata or {},
        }

        def _write_manifest(tmp):
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
        # chaos: a KILL here is the canonical kill-mid-commit -- data
        # files staged, manifest absent -- which must cost at most one
        # step, never the job (tests/test_chaos.py, ci chaos stage)
        _chaos.fail_point("checkpoint.commit.pre_manifest", step=step,
                          path=staging)
        # manifest LAST: its presence asserts every data file above it
        # is complete, so the rename below publishes all-or-nothing
        commit(os.path.join(staging, MANIFEST_NAME), _write_manifest)
        _fsync_dir(staging)
        if os.path.isdir(final):        # re-saving an existing step
            shutil.rmtree(final)
        os.replace(staging, final)
        _fsync_dir(self.root)
        sweep_stale_tmps(self.root)
        # chaos: corruption AFTER the atomic publish models bit-rot or
        # a non-atomic foreign writer -- what manifest verification and
        # quarantine exist to catch
        _chaos.fail_point("checkpoint.commit.post_commit", step=step,
                          path=final)
        return total

    def _apply_retention(self):
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        keep_n = self.keep_every_n_steps
        candidates = [s for s in steps
                      if not (keep_n and s % keep_n == 0)]
        excess = len(candidates) - self.max_to_keep
        for step in candidates[:max(0, excess)]:
            shutil.rmtree(self.step_dir(step), ignore_errors=True)

    # -- restore -------------------------------------------------------
    def restore(self, step=None, sharding=None):
        """Load a checkpoint: the newest intact step when ``step`` is
        None (returning None if there is none at all), or exactly
        ``step`` (raising CheckpointError if it fails verification).

        ``sharding`` optionally maps restored arrays onto the *current*
        mesh: a callable ``(item, key, shape) -> jax.sharding.Sharding``
        (or None for host placement) applied to every array -- this is
        how a job resumes on a different topology than it saved from.
        """
        self.wait_until_finished()
        t0 = time.perf_counter()
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
            manifest = self._verify_step(step)
            if manifest is None:        # raced a concurrent delete
                return None
        else:
            step = int(step)
            manifest = self._verify_step(step)
            if manifest is None:
                raise CheckpointError(
                    "checkpoint step %d failed verification" % step)
        dirpath = self.step_dir(step)
        if any(e.get("kind") == "shard"
               for e in manifest["files"].values()):
            from . import sharded
            items, nbytes = sharded.restore_sharded(
                dirpath, manifest, sharding=sharding)
        else:
            items = {}
            nbytes = 0
            for fname, entry in sorted(manifest["files"].items()):
                items[entry.get("item", fname)] = \
                    read_item(dirpath, fname, entry)
                nbytes += entry.get("bytes", 0)
            if sharding is not None:
                items = _apply_sharding(items, sharding)
        if _telemetry._ENABLED:
            _telemetry.hooks.checkpoint(
                "restore", nbytes=nbytes,
                seconds=time.perf_counter() - t0, step=step,
                root=self.root)
        return Checkpoint(step, items, manifest.get("metadata", {}))

    # -- training-loop conveniences ------------------------------------
    def save_training(self, step, block, trainer=None, metadata=None):
        """Checkpoint a Gluon block (+ optional Trainer state)."""
        items = {"params": {k: p._reduce() for k, p in
                            block._collect_params_with_prefix().items()
                            if p._data is not None}}
        if trainer is not None:
            items["trainer"] = trainer.get_states()
        self.save(step, items, metadata=metadata)

    def restore_training(self, block, trainer=None, step=None, ctx=None):
        """Restore :meth:`save_training` state in place.  Returns the
        Checkpoint (or None on a fresh start)."""
        ckpt = self.restore(step=step)
        if ckpt is None:
            return None
        params = ckpt.items.get("params")
        if params is not None:
            _load_block_params(block, params, ctx=ctx)
        if trainer is not None and "trainer" in ckpt.items:
            trainer.set_states(ckpt.items["trainer"])
        return ckpt

    # -- async plumbing ------------------------------------------------
    def wait_until_finished(self):
        """Block until any in-flight async save has committed; re-raises
        the writer's error if it failed."""
        if self._writer is not None:
            self._writer.wait_until_finished()

    def close(self):
        self.wait_until_finished()


def _apply_sharding(items, sharding):
    import jax
    from .. import ndarray as nd
    out = {}
    for name, value in items.items():
        if not isinstance(value, dict):
            out[name] = value
            continue
        placed = {}
        for k, v in value.items():
            arr = v.asnumpy() if isinstance(v, nd.NDArray) else v
            s = sharding(name, k, arr.shape) if callable(sharding) \
                else sharding.get((name, k)) if isinstance(sharding, dict) \
                else sharding
            placed[k] = nd.NDArray(jax.device_put(arr, s)) \
                if s is not None else nd.NDArray(arr)
        out[name] = placed
    return out


def _load_block_params(block, params, ctx=None):
    """Assign a restored params dict onto a block by structural name
    (same contract as Block.load_parameters, but from in-memory
    arrays)."""
    from .. import ndarray as nd
    targets = block._collect_params_with_prefix()
    for name, data in params.items():
        if name not in targets:
            raise CheckpointError(
                "restored parameter %r not found in block" % name)
        p = targets[name]
        if not isinstance(data, nd.NDArray):
            data = nd.NDArray(data)
        if p._data is None:
            p._shape = data.shape
            p._deferred_init = None
            p._data = data
            if p._grad_req != "null":
                p._init_grad()
        else:
            p._data._data = data.as_in_context(p._data.context)._data
