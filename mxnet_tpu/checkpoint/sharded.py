"""Multi-process sharded checkpoints (ISSUE 3 layer 3).

Under a multi-process ``parallel/mesh.py`` run a parameter is ONE
global ``jax.Array`` whose shards live across hosts; no single process
can (or should) serialize it alone.  The layout here:

- every process writes only its **addressable** shards -- and of those
  only the ``replica_id == 0`` copies, so replicated axes are stored
  once -- into ``<item>.shard<rank>.params`` plus a
  ``<item>.shard<rank>.json`` index mapping each stored entry to its
  ``(key, global_shape, dtype, slices)``;
- all processes rendezvous (``kvstore.barrier()`` semantics --
  ``distributed.barrier``), then **process 0 alone** digests every
  staged file and commits the merged manifest + directory rename, so
  the commit point stays a single atomic ``os.replace``;
- restore reads *all* shard files, reassembles each parameter into its
  global array, and places it onto the **current** mesh via the
  caller's ``sharding`` -- the saved topology is recorded in the
  manifest but never required to match, so a job preempted on one
  topology can resume on another.

Single-process runs degrade cleanly (every shard is addressable,
rank 0 is the only writer); the machinery is identical, which is what
the test suite exercises on 8 virtual CPU devices.
"""
from __future__ import annotations

import json
import os
import shutil

import numpy as np

import jax

from . import core as _core

__all__ = ["save_sharded", "restore_sharded"]


def _world():
    from ..distributed import world
    try:
        return world()
    except Exception:
        return 1, 0


def _barrier(nprocs, tag):
    if nprocs > 1:
        from ..distributed import barrier
        barrier("ckpt_%s" % tag)


def _index_of(shard, shape):
    """JSON-able [start, stop] per dim of one shard's slice into the
    global array (a full slice materializes its bounds)."""
    out = []
    for sl, dim in zip(shard.index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _local_shards(value):
    """(global_shape, dtype, [(index, np_data), ...]) of the shards this
    process must write.  Non-jax values (numpy, NDArray on one device)
    count as one full shard owned by rank 0's replica."""
    from .. import ndarray as nd
    if isinstance(value, nd.NDArray):
        value = value._data
    if isinstance(value, jax.Array):
        shape = tuple(value.shape)
        shards = [(_index_of(s, shape), np.asarray(s.data))
                  for s in value.addressable_shards if s.replica_id == 0]
        return shape, np.dtype(value.dtype), shards
    arr = np.asarray(value)
    shape = tuple(arr.shape)
    index = [[0, d] for d in shape]
    return shape, arr.dtype, [(index, arr)]


def save_sharded(manager, step, items, metadata):
    """Stage + commit one sharded step under ``manager.root``.  Every
    process calls this with the same ``step``/``items``; returns the
    bytes written *by this process* (manifest totals cover all ranks).

    The staging directory name is deterministic (no pid suffix) so all
    ranks address the same dir; rank 0 creates and commits it.
    """
    from .. import ndarray as nd
    nprocs, rank = _world()
    final = manager.step_dir(step)
    staging = final + ".shared.tmp"
    if rank == 0:
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
    _barrier(nprocs, "stage")

    nd.waitall()
    written = 0
    for name, value in sorted(items.items()):
        if isinstance(value, (bytes, bytearray, memoryview)):
            if rank == 0:               # opaque blobs are rank-0 state
                fname = name + ".bin"
                # staging dir: atomicity comes from the directory
                # rename at commit, not per-file temps
                with open(os.path.join(staging, fname), "wb") as f:  # mxlint: disable=bare-state-write
                    f.write(bytes(value))
                written += len(value)
            continue
        payload = {}
        index = {}
        for key, arr in value.items():
            shape, dtype, shards = _local_shards(arr)
            entry = {"global_shape": list(shape), "dtype": dtype.name
                     if dtype.names is None else str(dtype),
                     "slices": []}
            for i, (sl, data) in enumerate(shards):
                skey = "%s@%d" % (key, i)
                payload[skey] = data
                entry["slices"].append({"key": skey, "index": sl})
            index[key] = entry
        fname = "%s.shard%05d.params" % (name, rank)
        nd.save(os.path.join(staging, fname), payload)
        with open(os.path.join(staging, fname[:-7] + ".json"), "w") as f:
            json.dump({"item": name, "rank": rank, "params": index}, f)
        for suffix in (fname, fname[:-7] + ".json"):
            nbytes, _ = _core._fsync_and_digest(
                os.path.join(staging, suffix))
            written += nbytes

    _barrier(nprocs, "written")
    if rank == 0:
        files = {}
        for fname in sorted(os.listdir(staging)):
            nbytes, crc = _core.file_digest(os.path.join(staging, fname))
            kind = "shard" if ".shard" in fname else "bin"
            item = fname.split(".shard")[0] if kind == "shard" \
                else fname.rsplit(".", 1)[0]
            files[fname] = {"bytes": nbytes, "crc32": crc, "kind": kind,
                            "item": item}
        manifest = {
            "format_version": _core.FORMAT_VERSION,
            "step": int(step),
            "files": files,
            "topology": {"num_processes": int(nprocs),
                         "process_id": 0,
                         "num_devices": jax.device_count()},
            "metadata": metadata or {},
        }

        def _write_manifest(tmp):
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
        _core.commit(os.path.join(staging, _core.MANIFEST_NAME),
                     _write_manifest)
        _core._fsync_dir(staging)
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.replace(staging, final)
        _core._fsync_dir(manager.root)
    _barrier(nprocs, "committed")
    return written


def restore_sharded(dirpath, manifest, sharding=None):
    """Reassemble a sharded step into full arrays and (optionally)
    reshard them onto the current mesh.

    Returns ``(items, nbytes_read)``.  ``sharding`` follows
    :meth:`CheckpointManager.restore`: a callable
    ``(item, key, shape) -> Sharding``, a ``{(item, key): Sharding}``
    dict, a single Sharding, or None (host arrays).
    """
    from .. import ndarray as nd
    files = manifest["files"]
    items = {}
    nbytes = 0
    # group shard indexes by item
    shard_indexes = {}
    for fname, entry in sorted(files.items()):
        nbytes += entry.get("bytes", 0)
        if entry.get("kind") == "bin":
            items[entry.get("item", fname)] = \
                _core.read_item(dirpath, fname, entry)
            continue
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(dirpath, fname)) as f:
            idx = json.load(f)
        shard_indexes.setdefault(idx["item"], []).append(
            (fname[:-5] + ".params", idx["params"]))
    for item, parts in sorted(shard_indexes.items()):
        assembled = {}
        for fname, index in parts:
            payload = nd.load(os.path.join(dirpath, fname))
            for key, entry in index.items():
                shape = tuple(entry["global_shape"])
                if key not in assembled:
                    assembled[key] = np.empty(
                        shape, dtype=_np_dtype(entry["dtype"]))
                full = assembled[key]
                for sl in entry["slices"]:
                    region = tuple(slice(a, b) for a, b in sl["index"])
                    data = payload[sl["key"]].asnumpy()
                    if shape == ():
                        assembled[key] = data.reshape(())
                    else:
                        full[region] = data
        placed = {}
        for key, arr in sorted(assembled.items()):
            s = sharding(item, key, arr.shape) if callable(sharding) \
                else sharding.get((item, key)) \
                if isinstance(sharding, dict) else sharding
            if s is not None:
                # every rank assembled the FULL global value above, so
                # placement onto a (possibly multi-host) mesh goes
                # through the shared staging helper -- device_put when
                # fully addressable, per-process shard assembly on a
                # global mesh (reshard-on-restore across topologies)
                from ..parallel.mesh import put_replicated
                placed[key] = nd.NDArray(put_replicated(arr, s))
            else:
                placed[key] = nd.NDArray(arr)
        items[item] = placed
    return items, nbytes


def _np_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp
        if name == "bfloat16":
            return np.dtype(jnp.bfloat16.dtype)
        raise
