"""Multi-process sharded checkpoints (ISSUE 3 layer 3; rank-death-safe
commit protocol since ISSUE 15).

Under a multi-process ``parallel/mesh.py`` run a parameter is ONE
global ``jax.Array`` whose shards live across hosts; no single process
can (or should) serialize it alone.  The layout here:

- every process writes only its **addressable** shards -- and of those
  only the ``replica_id == 0`` copies, so replicated axes are stored
  once -- into ``<item>.shard<rank>.params`` plus a
  ``<item>.shard<rank>.json`` index mapping each stored entry to its
  ``(key, global_shape, dtype, slices)``; each file lands through a
  pid-suffixed temp + rename, so a killed rank leaves ``*.tmp`` crumbs
  (swept by the next save), never a plausible-looking partial shard;
- all processes rendezvous at three **attributed barriers**
  (``distributed.barrier`` -- a timeout raises a typed
  ``BarrierTimeout`` naming the missing rank, never a raw jaxlib
  deadline): ``stage`` after the staging dir exists, ``written`` after
  every rank's shards are durable, and ``committed`` -- the commit
  GATE: **process 0 stages the merged manifest, then the whole world
  confirms at "committed" BEFORE the atomic directory rename**.  A
  rank dead anywhere up to that gate means the rename never happens --
  the PR-3 manifest-last invariant extended across ranks: a torn step
  is impossible, a rank death costs at most one step.  (The rename
  happens *after* the gate, so on ranks != 0 a returned save precedes
  global visibility by an instant -- a reader that needs the step
  visible right after ``save`` rendezvouses first, e.g.
  ``distributed.barrier("published")``);
- a failed save aborts *cleanly* on every survivor: the staging dir is
  swept, ``checkpoint.commit_aborted`` counts it, a failing-but-alive
  rank posts an abort ack (``distributed.post_abort``) so peers fail
  fast instead of waiting out the barrier bound, and the typed error
  propagates for the caller's policy (continue past the failed publish
  or surface to the restart supervisor -- ``serving.loop``);
- restore reads *all* shard files, reassembles each parameter into its
  global array, and places it onto the **current** mesh via the
  caller's ``sharding`` -- the saved topology is recorded in the
  manifest but never required to match, so a job preempted on one
  topology can resume on another.

Chaos fail points (docs/chaos.md) cover every dangerous spot: each
barrier (``checkpoint.sharded.barrier.<tag>``), the per-rank shard
write (``checkpoint.sharded.shard_write``), and the merged-manifest
commit (``checkpoint.sharded.commit``).

Single-process runs degrade cleanly (every shard is addressable,
rank 0 is the only writer, barriers are no-ops); the machinery is
identical, which is what the test suite exercises on 8 virtual CPU
devices.
"""
from __future__ import annotations

import json
import os
import re
import shutil

import numpy as np

import jax

from .. import chaos as _chaos
from .. import telemetry as _telemetry
from . import core as _core

__all__ = ["save_sharded", "restore_sharded", "sweep_shared_staging"]

_SHARED_STAGING_RE = re.compile(r"^step_\d{8}\.shared\.tmp$")
_OWNER_PREFIX = ".owner."


def _world():
    from ..distributed import world
    try:
        return world()
    except Exception:
        return 1, 0


def _barrier(nprocs, tag, step=None):
    if nprocs > 1:
        from ..distributed import barrier
        # chaos: a KILL here is a rank dying AT the rendezvous -- the
        # previous phase's work done, the ack never posted; survivors
        # must abort with a typed BarrierTimeout naming this rank
        _chaos.fail_point("checkpoint.sharded.barrier." + tag,
                          tag=tag, step=step)
        barrier("ckpt_%s" % tag)


def sweep_shared_staging(root):
    """Remove ``step_<N>.shared.tmp`` staging dirs left by a dead
    sharded save -- the multi-rank analog of ``core.sweep_stale_tmps``.
    The shared staging name carries no pid (all ranks address one
    dir), so liveness rides the ``.owner.<pid>`` marker rank 0 drops
    at creation: a dir whose owner is dead -- or that has no marker at
    all -- is torn down; a live owner's dir is in flight and left
    alone (but its *interior* dead-pid ``*.tmp`` shard crumbs, a
    killed rank's partial write, are swept).  Returns removed paths.
    """
    removed = []
    try:
        entries = os.listdir(root)
    except OSError:
        return removed
    for name in entries:
        if not _SHARED_STAGING_RE.match(name):
            continue
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        owner = None
        try:
            for inner in os.listdir(path):
                if inner.startswith(_OWNER_PREFIX):
                    owner = int(inner[len(_OWNER_PREFIX):])
                    break
        except (OSError, ValueError):
            pass
        if owner is not None and (owner == os.getpid()
                                  or _core._pid_alive(owner)):
            removed.extend(_core.sweep_stale_tmps(path))
            continue
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
        _chaos.survived("checkpoint.sharded.shard_write", "sweep")
    return removed


def _index_of(shard, shape):
    """JSON-able [start, stop] per dim of one shard's slice into the
    global array (a full slice materializes its bounds)."""
    out = []
    for sl, dim in zip(shard.index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _local_shards(value):
    """(global_shape, dtype, [(index, np_data), ...]) of the shards this
    process must write.  Non-jax values (numpy, NDArray on one device)
    count as one full shard owned by rank 0's replica."""
    from .. import ndarray as nd
    if isinstance(value, nd.NDArray):
        value = value._data
    if isinstance(value, jax.Array):
        shape = tuple(value.shape)
        shards = [(_index_of(s, shape), np.asarray(s.data))
                  for s in value.addressable_shards if s.replica_id == 0]
        return shape, np.dtype(value.dtype), shards
    arr = np.asarray(value)
    shape = tuple(arr.shape)
    index = [[0, d] for d in shape]
    return shape, arr.dtype, [(index, arr)]


def save_sharded(manager, step, items, metadata):
    """Stage + commit one sharded step under ``manager.root``.  Every
    process calls this with the same ``step``/``items``; returns the
    bytes written *by this process* (manifest totals cover all ranks).

    The staging directory name is deterministic (no pid suffix) so all
    ranks address the same dir; rank 0 creates and commits it.  Any
    failure -- a peer dead at a barrier, a local write error, an
    injected fault -- aborts the whole save cleanly (see
    :func:`_abort_save`); the manifest is only ever renamed into place
    after EVERY rank confirmed at the "committed" gate.
    """
    from ..distributed import RankFailure
    nprocs, rank = _world()
    final = manager.step_dir(step)
    staging = final + ".shared.tmp"
    # the gate every survivor re-raises through; "stage" until the
    # first barrier passes, None once the commit gate has been crossed
    pending_gate = ["stage"]
    try:
        return _save_sharded_inner(manager, step, items, metadata,
                                   nprocs, rank, final, staging,
                                   pending_gate)
    except BaseException as e:
        if isinstance(e, Exception):
            _abort_save(e, step, staging, nprocs, rank, pending_gate[0],
                        RankFailure)
        raise


def _save_sharded_inner(manager, step, items, metadata, nprocs, rank,
                        final, staging, pending_gate):
    from .. import ndarray as nd
    if rank == 0:
        # dead predecessors first (a killed world's staging, ISSUE 15
        # satellite), then this step's own leftover
        sweep_shared_staging(manager.root)
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
        with open(os.path.join(staging,
                               _OWNER_PREFIX + str(os.getpid())),
                  "w"):
            pass
    _barrier(nprocs, "stage", step)
    pending_gate[0] = "written"

    nd.waitall()
    written = 0
    for name, value in sorted(items.items()):
        # chaos: a KILL here is a rank dying mid-shard-write --
        # pid-tmp crumbs on disk, no "written" ack; survivors abort at
        # the next barrier and the crumbs are swept by the next save
        _chaos.fail_point("checkpoint.sharded.shard_write", item=name,
                          rank=rank, step=step, path=staging)
        if isinstance(value, (bytes, bytearray, memoryview)):
            if rank == 0:               # opaque blobs are rank-0 state
                written += _stage_file(staging, name + ".bin",
                                       lambda p: _write_bytes(p, value))
            continue
        payload = {}
        index = {}
        for key, arr in value.items():
            shape, dtype, shards = _local_shards(arr)
            entry = {"global_shape": list(shape), "dtype": dtype.name
                     if dtype.names is None else str(dtype),
                     "slices": []}
            for i, (sl, data) in enumerate(shards):
                skey = "%s@%d" % (key, i)
                payload[skey] = data
                entry["slices"].append({"key": skey, "index": sl})
            index[key] = entry
        fname = "%s.shard%05d.params" % (name, rank)
        written += _stage_file(staging, fname,
                               lambda p: nd.save(p, payload))
        written += _stage_file(
            staging, fname[:-7] + ".json",
            lambda p: _write_json(p, {"item": name, "rank": rank,
                                      "params": index}))

    _barrier(nprocs, "written", step)
    pending_gate[0] = "committed"
    if rank == 0:
        files = {}
        for fname in sorted(os.listdir(staging)):
            if fname.startswith("."):
                continue                # the .owner.<pid> marker
            nbytes, crc = _core.file_digest(os.path.join(staging, fname))
            kind = "shard" if ".shard" in fname else "bin"
            item = fname.split(".shard")[0] if kind == "shard" \
                else fname.rsplit(".", 1)[0]
            files[fname] = {"bytes": nbytes, "crc32": crc, "kind": kind,
                            "item": item}
        manifest = {
            "format_version": _core.FORMAT_VERSION,
            "step": int(step),
            "files": files,
            "topology": {"num_processes": int(nprocs),
                         "process_id": 0,
                         "num_devices": jax.device_count()},
            "metadata": metadata or {},
        }

        def _write_manifest(tmp):
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
        # chaos: a KILL here is the coordinator dying mid-merge --
        # every shard durable, no manifest; survivors time out at the
        # "committed" gate naming rank 0 and the save costs one step
        _chaos.fail_point("checkpoint.sharded.commit", step=step,
                          path=staging)
        _core.commit(os.path.join(staging, _core.MANIFEST_NAME),
                     _write_manifest)
        _core._fsync_dir(staging)
    # the commit GATE (cross-rank manifest-last invariant): the staged
    # manifest becomes visible ONLY after every rank confirms it got
    # this far -- a rank dead between "written" and here leaves the
    # manifest staged in a *.shared.tmp dir discovery never reads, so
    # the torn step is impossible and latest_step() falls back one step
    _barrier(nprocs, "committed", step)
    pending_gate[0] = None
    if rank == 0:
        try:
            os.remove(os.path.join(staging,
                                   _OWNER_PREFIX + str(os.getpid())))
        except OSError:
            pass
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.replace(staging, final)
        _core._fsync_dir(manager.root)
    return written


def _stage_file(staging, fname, write_fn):
    """Write one staged file through a pid-suffixed temp + fsync +
    rename, so a rank killed mid-write leaves only an obvious ``*.tmp``
    crumb (swept by :func:`sweep_shared_staging`), never a torn file
    under a final name.  Returns the bytes written."""
    tmp = os.path.join(staging, "%s.%d.tmp" % (fname, os.getpid()))
    write_fn(tmp)
    nbytes, _crc = _core._fsync_and_digest(tmp)
    os.replace(tmp, os.path.join(staging, fname))
    return nbytes


def _write_bytes(path, value):
    # staging dir: atomicity comes from the pid-tmp rename in
    # _stage_file plus the directory rename at commit
    with open(path, "wb") as f:  # mxlint: disable=bare-state-write
        f.write(bytes(value))


def _write_json(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)


def _abort_save(exc, step, staging, nprocs, rank, gate, rank_failure):
    """Clean abort on every survivor: tell peers (a failing-but-alive
    rank posts an abort ack at the gate they will wait on next, so
    they fail fast instead of timing out), sweep the staging dir, and
    count ``checkpoint.commit_aborted`` -- the caller re-raises the
    typed error for its publish policy."""
    if gate is not None and nprocs > 1 \
            and not isinstance(exc, rank_failure):
        # a local failure (write error, injected RAISE): peers are
        # healthy and heading for the next barrier -- abort it
        from ..distributed import post_abort
        try:
            post_abort("ckpt_%s" % gate, reason=type(exc).__name__)
        except Exception:
            pass
    shutil.rmtree(staging, ignore_errors=True)
    if _telemetry._ENABLED:
        _telemetry.hooks.checkpoint_commit_aborted(
            step, "%s: %s" % (type(exc).__name__, exc), rank=rank)
    # survival accounting: the abort path IS the recovery -- pair the
    # survived count with the fail point that made the weather
    if isinstance(exc, rank_failure):
        tag = getattr(exc, "tag", "") or ""
        point = "checkpoint.sharded.barrier." + tag[5:] \
            if tag.startswith("ckpt_") else "checkpoint.sharded.commit"
    elif getattr(exc, "point", None):     # an injected local fault
        point = exc.point
    else:
        point = "checkpoint.sharded.commit"
    _chaos.survived(point, "abort")


def restore_sharded(dirpath, manifest, sharding=None):
    """Reassemble a sharded step into full arrays and (optionally)
    reshard them onto the current mesh.

    Returns ``(items, nbytes_read)``.  ``sharding`` follows
    :meth:`CheckpointManager.restore`: a callable
    ``(item, key, shape) -> Sharding``, a ``{(item, key): Sharding}``
    dict, a single Sharding, or None (host arrays).
    """
    from .. import ndarray as nd
    files = manifest["files"]
    items = {}
    nbytes = 0
    # group shard indexes by item
    shard_indexes = {}
    for fname, entry in sorted(files.items()):
        nbytes += entry.get("bytes", 0)
        if entry.get("kind") == "bin":
            items[entry.get("item", fname)] = \
                _core.read_item(dirpath, fname, entry)
            continue
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(dirpath, fname)) as f:
            idx = json.load(f)
        shard_indexes.setdefault(idx["item"], []).append(
            (fname[:-5] + ".params", idx["params"]))
    for item, parts in sorted(shard_indexes.items()):
        assembled = {}
        for fname, index in parts:
            payload = nd.load(os.path.join(dirpath, fname))
            for key, entry in index.items():
                shape = tuple(entry["global_shape"])
                if key not in assembled:
                    assembled[key] = np.empty(
                        shape, dtype=_np_dtype(entry["dtype"]))
                full = assembled[key]
                for sl in entry["slices"]:
                    region = tuple(slice(a, b) for a, b in sl["index"])
                    data = payload[sl["key"]].asnumpy()
                    if shape == ():
                        assembled[key] = data.reshape(())
                    else:
                        full[region] = data
        placed = {}
        for key, arr in sorted(assembled.items()):
            s = sharding(item, key, arr.shape) if callable(sharding) \
                else sharding.get((item, key)) \
                if isinstance(sharding, dict) else sharding
            if s is not None:
                # every rank assembled the FULL global value above, so
                # placement onto a (possibly multi-host) mesh goes
                # through the shared staging helper -- device_put when
                # fully addressable, per-process shard assembly on a
                # global mesh (reshard-on-restore across topologies)
                from ..parallel.mesh import put_replicated
                placed[key] = nd.NDArray(put_replicated(arr, s))
            else:
                placed[key] = nd.NDArray(arr)
        items[item] = placed
    return items, nbytes


def _np_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp
        if name == "bfloat16":
            return np.dtype(jnp.bfloat16.dtype)
        raise
