"""Profiler (reference: ``python/mxnet/profiler.py`` over
``src/profiler/profiler.cc``).

TPU-native design: the heavy lifting is ``jax.profiler`` -- XLA already
records per-op device timelines, HBM usage, and host/device transfer
events into a TensorBoard-loadable trace, which replaces the reference's
hand-rolled chrome-tracing writer.  This module supplies the reference's
control surface (``set_config / set_state / start / stop / dump``) plus
named scopes that executors and the imperative dispatcher enter so
framework-level structure (op names, cached-graph steps) shows up in the
device trace.
"""
from __future__ import annotations

import contextlib
import os

from .base import MXNetError

_config = {
    "filename": "profile.json",   # reference arg; dir is derived from it
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": True,
    "profile_api": True,
    "aggregate_stats": False,
}
_state = "stop"
_trace_dir = None
_scopes_enabled = False


def set_config(**kwargs):
    """Reference: ``profiler.set_config``.  ``filename`` determines the
    trace output directory (its dirname; traces are TensorBoard format,
    not a single json)."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError("profiler.set_config: unknown options %r"
                         % sorted(unknown))
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    """Reference: ``profiler.set_state('run'|'stop')``."""
    global _state, _trace_dir, _scopes_enabled
    if state not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")
    if state == "run" and _state == "stop":
        import jax
        _trace_dir = os.path.dirname(
            os.path.abspath(_config["filename"])) or "."
        _trace_dir = os.path.join(_trace_dir, "mxnet_tpu_profile")
        os.makedirs(_trace_dir, exist_ok=True)
        jax.profiler.start_trace(_trace_dir)
        _scopes_enabled = True
        _state = "run"
    elif state == "stop" and _state == "run":
        import jax
        jax.profiler.stop_trace()
        _scopes_enabled = False
        _state = "stop"


def start(profile_process="worker"):
    """Reference: ``profiler.start``."""
    set_state("run", profile_process)


def stop(profile_process="worker"):
    """Reference: ``profiler.stop``."""
    set_state("stop", profile_process)


def pause(profile_process="worker"):
    """Scopes off; device trace keeps running (closest analog)."""
    global _scopes_enabled
    _scopes_enabled = False


def resume(profile_process="worker"):
    global _scopes_enabled
    if _state == "run":
        _scopes_enabled = True


def dump(finished=True, profile_process="worker"):
    """Reference: ``profiler.dump`` -- finalize the trace to disk.  The
    trace directory (TensorBoard `plugins/profile` layout) is returned."""
    if _state == "run" and finished:
        stop()
    return _trace_dir


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Reference: ``profiler.dumps`` (aggregate stats).  Aggregation
    lives in the TensorBoard profile; this returns a pointer string."""
    return ("profile trace: %s (load with TensorBoard's profile plugin)"
            % (_trace_dir or "<not started>"))


def state():
    return _state


@contextlib.contextmanager
def scope(name):
    """Named region; shows up in the XLA device trace (reference:
    profiler scope in ``MXNET_PROFILER_SCOPE``)."""
    if not _scopes_enabled:
        yield
        return
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


class Profiler:
    """Context manager sugar: ``with mx.profiler.Profiler(filename=...):``"""

    def __init__(self, **config):
        if config:
            set_config(**config)

    def __enter__(self):
        start()
        return self

    def __exit__(self, *exc):
        stop()
