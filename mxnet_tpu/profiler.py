"""Profiler (reference: ``python/mxnet/profiler.py`` over
``src/profiler/profiler.cc``).

TPU-native design: the heavy lifting is ``jax.profiler`` -- XLA already
records per-op device timelines, HBM usage, and host/device transfer
events into a TensorBoard-loadable trace, which replaces the reference's
hand-rolled chrome-tracing writer.  This module supplies the reference's
control surface (``set_config / set_state / start / stop / dump``) plus
named scopes that executors and the imperative dispatcher enter so
framework-level structure (op names, cached-graph steps) shows up in the
device trace.
"""
from __future__ import annotations

import contextlib
import os

from .base import MXNetError

_config = {
    "filename": "profile.json",   # reference arg; dir is derived from it
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": True,
    "profile_api": True,
    "aggregate_stats": False,
}
_state = "stop"
_trace_dir = None
_scopes_enabled = False


def set_config(**kwargs):
    """Reference: ``profiler.set_config``.  ``filename`` determines the
    trace output directory (its dirname; traces are TensorBoard format,
    not a single json)."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError("profiler.set_config: unknown options %r"
                         % sorted(unknown))
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    """Reference: ``profiler.set_state('run'|'stop')``."""
    global _state, _trace_dir, _scopes_enabled
    if state not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")
    if state == "run" and _state == "stop":
        import jax
        _trace_dir = os.path.dirname(
            os.path.abspath(_config["filename"])) or "."
        _trace_dir = os.path.join(_trace_dir, "mxnet_tpu_profile")
        os.makedirs(_trace_dir, exist_ok=True)
        jax.profiler.start_trace(_trace_dir)
        _scopes_enabled = True
        _state = "run"
    elif state == "stop" and _state == "run":
        import jax
        jax.profiler.stop_trace()
        _scopes_enabled = False
        _state = "stop"


def start(profile_process="worker"):
    """Reference: ``profiler.start``."""
    set_state("run", profile_process)


def stop(profile_process="worker"):
    """Reference: ``profiler.stop``."""
    set_state("stop", profile_process)


def pause(profile_process="worker"):
    """Scopes off; device trace keeps running (closest analog)."""
    global _scopes_enabled
    _scopes_enabled = False


def resume(profile_process="worker"):
    global _scopes_enabled
    if _state == "run":
        _scopes_enabled = True


def dump(finished=True, profile_process="worker"):
    """Reference: ``profiler.dump`` -- finalize the trace to disk.  The
    trace directory (TensorBoard `plugins/profile` layout) is returned."""
    if _state == "run" and finished:
        stop()
    return _trace_dir


_DUMPS_SORT_KEYS = ("total", "avg", "min", "max", "count", "flops",
                    "bytes", "peak_hbm")


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Reference: ``profiler.dumps`` (aggregate stats) -- REAL per-
    executable aggregates from the mx.profiling CostReport store, not a
    pointer string.  One row per captured compiled program: step
    count/total/avg (host wall), FLOPs, bytes accessed, peak HBM.

    ``sort_by`` follows the reference's keys (``total``/``avg``/
    ``min``/``max``/``count`` over step time) plus cost-side keys
    (``flops``/``bytes``/``peak_hbm``); ``format`` is ``table`` or
    ``json``; ``reset=True`` clears the store after rendering."""
    if sort_by not in _DUMPS_SORT_KEYS:
        raise MXNetError("profiler.dumps: sort_by must be one of %s"
                         % (_DUMPS_SORT_KEYS,))
    if format not in ("table", "json"):
        raise MXNetError("profiler.dumps: format must be 'table' or "
                         "'json'")
    from . import profiling
    rows = []
    for rep in profiling.reports():
        st = rep.get("step") or {}
        count = st.get("count", 0)
        total = st.get("total_s", 0.0) or 0.0
        rows.append({
            "name": rep["label"],
            "count": count,
            "total": total,
            "avg": (total / count) if count else 0.0,
            "min": st.get("min_s") or 0.0,
            "max": st.get("max_s") or 0.0,
            "flops": rep["totals"]["flops"],
            "bytes": rep["totals"]["bytes_accessed"],
            "peak_hbm": rep["memory"]["peak_hbm_bytes"],
        })
    rows.sort(key=lambda r: r[sort_by], reverse=not ascending)
    if reset:
        profiling.reset()
    if format == "json":
        import json
        return json.dumps(rows, indent=1, sort_keys=True)
    lines = ["Profile Statistics (mx.profiling cost reports):",
             "%-36s %8s %12s %12s %14s %14s %12s"
             % ("Name", "Count", "Total(ms)", "Avg(ms)", "FLOPs",
                "Bytes", "PeakHBM")]
    for r in rows:
        lines.append("%-36s %8d %12.3f %12.3f %14.3g %14.3g %12d"
                     % (r["name"][:36], r["count"], 1e3 * r["total"],
                        1e3 * r["avg"], r["flops"], r["bytes"],
                        r["peak_hbm"]))
    if not rows:
        lines.append("(no cost reports captured; enable with "
                     "MXNET_TPU_PROFILING=1 / mx.profiling.enable())")
    return "\n".join(lines)


def state():
    return _state


@contextlib.contextmanager
def scope(name):
    """Named region.  Shows up in the XLA device trace (reference:
    profiler scope in ``MXNET_PROFILER_SCOPE``), AND -- via
    ``jax.named_scope`` -- in the ``op_name`` metadata of any HLO
    traced inside it, which is how framework provenance reaches the
    mx.profiling CostReport's per-scope attribution.  With
    mx.profiling enabled it also lands as a span on the step
    timeline."""
    from . import profiling as _profiling
    if not _scopes_enabled and not _profiling._ENABLED:
        yield
        return
    with contextlib.ExitStack() as stack:
        if _scopes_enabled:
            import jax
            stack.enter_context(jax.profiler.TraceAnnotation(name))
            stack.enter_context(jax.named_scope(name))
        if _profiling._ENABLED:
            from .profiling import timeline
            stack.enter_context(timeline.span(name))
        yield


class Profiler:
    """Context manager sugar: ``with mx.profiler.Profiler(filename=...):``"""

    def __init__(self, **config):
        if config:
            set_config(**config)

    def __enter__(self):
        start()
        return self

    def __exit__(self, *exc):
        stop()


class Domain:
    """Reference: ``profiler.Domain`` -- a named grouping for custom
    objects."""

    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name


def _region_name(a, b):
    """Reference calling conventions: ``Task(domain, name)`` /
    ``Frame(domain, name)`` take the Domain first; ``Event(name)`` takes
    just a name.  Accept both orders."""
    if b is None:
        return str(a)
    return "%s::%s" % (a, b) if isinstance(a, Domain) else str(b)


class _NamedRegion:
    """Base for the reference's custom profiler objects (``Task``,
    ``Frame``, ``Event``): start/stop (or ``with``) brackets a named
    region in the device trace."""

    def __init__(self, domain_or_name, name=None):
        self.name = _region_name(domain_or_name, name)
        self._cm = None

    def start(self):
        if _scopes_enabled:
            import jax
            self._cm = jax.profiler.TraceAnnotation(self.name)
            self._cm.__enter__()

    def stop(self):
        if self._cm is not None:
            self._cm.__exit__(None, None, None)
            self._cm = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_NamedRegion):
    """Reference: ``profiler.Task``."""


class Frame(_NamedRegion):
    """Reference: ``profiler.Frame``."""


class Event(_NamedRegion):
    """Reference: ``profiler.Event``."""


# profiler counters live in the telemetry registry under this prefix,
# so they show up in telemetry sinks/snapshots and ``profiler.reset()``
# can clear exactly them
_COUNTER_PREFIX = "profiler."


class Counter:
    """Named counter (reference: ``profiler.Counter(domain, name,
    value)``).  Re-constructing an existing name attaches to it without
    resetting (reference semantics).

    Backed by the ``mx.telemetry`` registry (one store, visible in every
    telemetry sink) instead of the former class-global dict, which
    leaked values across instances AND across tests with no way to
    clear them; ``profiler.reset()`` now zeroes all profiler counters.
    """

    def __init__(self, domain_or_name, name=None, value=None):
        from . import telemetry
        self.name = _region_name(domain_or_name, name)
        self._counter = telemetry.counter(_COUNTER_PREFIX + self.name)
        if value is not None:
            self._counter.set(value)

    def set_value(self, value):
        self._counter.set(value)

    def increment(self, delta=1):
        self._counter.inc(delta)

    def decrement(self, delta=1):
        self._counter.dec(delta)

    @property
    def value(self):
        return self._counter.value


def reset():
    """Zero every ``profiler.Counter`` (test isolation; the former
    class-global dict had no reset and leaked across tests)."""
    from . import telemetry
    telemetry.reset(prefix=_COUNTER_PREFIX)


def marker(name, scope="process"):
    """Instant event (reference: ``profiler.Marker``/``set_marker``):
    recorded as a zero-length annotation."""
    if _scopes_enabled:
        import jax
        with jax.profiler.TraceAnnotation("marker:" + name):
            pass


# reference env: start profiling at import when requested; the trace
# only hits disk at stop, so flush at interpreter exit
if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") != "0":
    import atexit
    set_state("run")
    atexit.register(stop)
