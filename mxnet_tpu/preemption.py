"""Preemption-aware checkpointing (SURVEY §5 failure detection).

The reference's failure story is checkpoint/restart around engine
crashes; the TPU-native analog is **preemption**: maintenance events
deliver SIGTERM with a grace window.  ``install()`` arms a handler that,
on signal, drains in-flight device work and writes the model parameters
plus optimizer state, then lets the training loop exit cleanly via
``handler.triggered``; ``resume()`` restores both on restart.

Checkpoint layout: ``<prefix>-preempt.params`` (block parameters) and
``<prefix>-preempt.states`` (Trainer/updater state), plus
``<prefix>-preempt.meta`` (a tiny JSON with the step counter).
"""
from __future__ import annotations

import json
import os
import signal
import threading

from .base import MXNetError

__all__ = ["PreemptionHandler", "install", "resume"]


class PreemptionHandler:
    """Arm signal-triggered checkpointing for a training loop.

    Usage::

        handler = mx.preemption.install(prefix, net, trainer)
        for epoch in range(...):
            for batch in data:
                if handler.triggered:      # checkpoint already written
                    return
                step(...)
    """

    def __init__(self, prefix, block, trainer=None,
                 signals=(signal.SIGTERM,), extra_state=None):
        self.prefix = prefix
        self.block = block
        self.trainer = trainer
        self.extra_state = extra_state or {}
        self.triggered = False
        self.saved = False
        # RLock: the SIGTERM handler runs on the same thread and may
        # interrupt an explicit save_now() call mid-save
        self._lock = threading.RLock()
        self._prev = {}
        for sig in signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)

    # -- paths ---------------------------------------------------------
    @property
    def params_path(self):
        return self.prefix + "-preempt.params"

    @property
    def states_path(self):
        return self.prefix + "-preempt.states"

    @property
    def meta_path(self):
        return self.prefix + "-preempt.meta"

    # -- save ----------------------------------------------------------
    def save_now(self, step=None):
        """Drain pending device work and write the checkpoint.  Safe to
        call directly (e.g. at epoch boundaries) as well as from the
        signal path.

        Files are written to temp paths and renamed into place, with
        the meta file LAST -- ``resume`` gates on the meta file, so a
        SIGKILL at grace-window expiry can never leave a checkpoint
        that loads truncated."""
        from . import ndarray as nd
        with self._lock:
            if self.saved:
                return
            self.saved = True      # re-entrancy: signal during save
            nd.waitall()           # drain the async queue first

            def commit(path, write_fn):
                tmp = "%s.%d.tmp" % (path, os.getpid())
                write_fn(tmp)
                os.replace(tmp, path)

            commit(self.params_path, self.block.save_parameters)
            if self.trainer is not None:
                commit(self.states_path, self.trainer.save_states)
            meta = {"step": step, "extra": self.extra_state}

            def write_meta(tmp):
                with open(tmp, "w") as f:
                    json.dump(meta, f)
            commit(self.meta_path, write_meta)

    def _on_signal(self, signum, frame):
        self.triggered = True
        try:
            self.save_now()
        finally:
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)

    def uninstall(self):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev if prev is not None
                          else signal.SIG_DFL)
        self._prev = {}


def install(prefix=None, block=None, trainer=None,
            signals=(signal.SIGTERM,), extra_state=None):
    """Arm SIGTERM-triggered checkpointing; returns the handler.

    With ``prefix=None`` the prefix comes from the
    ``MXNET_CHECKPOINT_ON_SIGTERM`` env var (operator-armed jobs)."""
    if prefix is None:
        from . import env as _env
        prefix = _env.get("MXNET_CHECKPOINT_ON_SIGTERM")
        if not prefix:
            raise MXNetError("preemption.install: no prefix given and "
                             "MXNET_CHECKPOINT_ON_SIGTERM is unset")
    if block is None:
        raise MXNetError("preemption.install needs the block to save")
    return PreemptionHandler(prefix, block, trainer, signals=signals,
                             extra_state=extra_state)


def resume(prefix, block, trainer=None, ctx=None):
    """Restore a preemption checkpoint if one exists.

    Returns the saved meta dict (``{"step": ..., "extra": ...}``) or
    None when no checkpoint is present (fresh start).
    """
    params = prefix + "-preempt.params"
    states = prefix + "-preempt.states"
    meta_path = prefix + "-preempt.meta"
    # the meta file commits LAST in save_now: its presence proves the
    # whole checkpoint landed (no truncated-params loads)
    if not os.path.exists(meta_path) or not os.path.exists(params):
        return None
    block.load_parameters(params, ctx=ctx)
    if trainer is not None and os.path.exists(states):
        trainer.load_states(states)
    with open(meta_path) as f:
        return json.load(f)
