"""Preemption-aware checkpointing (SURVEY §5 failure detection).

The reference's failure story is checkpoint/restart around engine
crashes; the TPU-native analog is **preemption**: maintenance events
deliver SIGTERM with a grace window.  ``install()`` arms a handler that,
on signal, marks ``handler.triggered``; the checkpoint (model parameters
plus optimizer state) is written at the training loop's next *read* of
``handler.triggered`` -- a loop boundary, so the save can never observe
a torn, mid-``trainer.step()`` state the way an arbitrary-bytecode
signal-path save could.  Loops that cannot poll can opt into the
immediate in-handler save with ``save_in_handler=True``.  ``resume()``
restores everything on restart.

Checkpoint layout: ``<prefix>-preempt.params`` (block parameters) and
``<prefix>-preempt.states`` (Trainer/updater state), plus
``<prefix>-preempt.meta`` (JSON with the step counter AND the byte
size + CRC32 of each committed file).  File commits go through the
shared atomic helper (``mx.checkpoint.core.commit``); ``resume()``
verifies the data files against the meta's checksums, so a checkpoint
that bit-rotted (or was half-overwritten by an even older writer)
reads as "no checkpoint" instead of loading garbage.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import warnings

from . import chaos as _chaos
from . import obs as _obs
from . import sync as _sync
from . import telemetry as _telemetry
from .base import MXNetError
from .checkpoint import core as _ckpt

__all__ = ["PreemptionHandler", "install", "resume"]


class PreemptionHandler:
    """Arm signal-triggered checkpointing for a training loop.

    Usage::

        handler = mx.preemption.install(prefix, net, trainer)
        for epoch in range(...):
            for batch in data:
                if handler.triggered:      # checkpoint already written
                    return
                step(...)
    """

    def __init__(self, prefix, block, trainer=None,
                 signals=(signal.SIGTERM,), extra_state=None,
                 save_in_handler=False, fallback_after=20.0):
        self.prefix = prefix
        self.block = block
        self.trainer = trainer
        self.extra_state = extra_state or {}
        self.saved = False
        self.save_in_handler = save_in_handler
        # Deferred saves rely on the loop polling ``triggered``; a loop
        # blocked in a long dispatch would otherwise reach SIGKILL with
        # nothing written.  The fallback timer fires a last-resort save
        # after ``fallback_after`` seconds (None disables) -- possibly
        # mid-step, so it is PROVISIONAL: it does not set ``saved``, and
        # a later consistent boundary save overwrites it.
        self.fallback_after = fallback_after
        self._fallback_timer = None
        self._fallback_saved = False
        self._signal_seen = False
        self._saving = False
        self._in_handler = False
        # RLock: the SIGTERM handler runs on the same thread and may
        # interrupt an explicit save_now() call mid-save
        self._lock = _sync.RLock(name="preemption.handler")
        # a previous incarnation killed between write_fn(tmp) and
        # os.replace strands its temp forever; clean house on arm
        _ckpt.sweep_stale_tmps(os.path.dirname(self.prefix) or ".",
                               prefix=os.path.basename(self.prefix))
        self._prev = {}
        for sig in signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)

    @property
    def triggered(self):
        """True once a preemption signal arrived.  Reading this at the
        loop boundary is what performs the (deferred) checkpoint write:
        the state is guaranteed consistent there, unlike inside the
        signal handler which may fire mid ``trainer.step()``."""
        if self._signal_seen and not self.saved:
            self.save_now()
        return self._signal_seen

    # -- paths ---------------------------------------------------------
    @property
    def params_path(self):
        return self.prefix + "-preempt.params"

    @property
    def states_path(self):
        return self.prefix + "-preempt.states"

    @property
    def meta_path(self):
        return self.prefix + "-preempt.meta"

    # -- save ----------------------------------------------------------
    def save_now(self, step=None, provisional=False):
        """Drain pending device work and write the checkpoint.  Safe to
        call directly (e.g. at epoch boundaries) as well as from the
        signal path.

        ``provisional=True`` (the fallback timer's mode) marks a save
        that may have caught a mid-step state: it is written, but it
        does NOT set ``saved``, so the next boundary-triggered save
        re-saves a consistent snapshot over it.

        Files are written to temp paths and renamed into place, with
        the meta file LAST -- ``resume`` gates on the meta file, so a
        SIGKILL at grace-window expiry can never leave a checkpoint
        that loads truncated."""
        from . import ndarray as nd
        with self._lock:
            if self.saved or self._saving:
                return
            if provisional and self._fallback_saved:
                return
            self._saving = True    # re-entrancy: signal during save
            try:
                # the drain deliberately runs under the handler lock:
                # the lock is re-entered only by the SIGTERM handler on
                # THIS thread (RLock), never contended across threads,
                # and the saved state must not advance past the drain
                nd.waitall()  # mxlint: disable=blocking-under-lock
                if self._fallback_saved and not provisional:
                    # re-arm the meta-last atomicity gate before
                    # overwriting a provisional checkpoint: otherwise a
                    # SIGKILL mid-re-save could leave NEW params beside
                    # the OLD provisional states/meta, and resume()
                    # (which trusts the meta file) would load a
                    # mismatched pair.  Runs AFTER waitall so a device
                    # error cannot destroy the provisional checkpoint
                    # before the re-save even starts -- and clearing
                    # _fallback_saved lets the fallback path rewrite a
                    # checkpoint if THIS save fails partway.
                    self._fallback_saved = False
                    try:
                        os.remove(self.meta_path)
                    except FileNotFoundError:
                        pass

                # shared atomic commit (tmp+fsync+rename) from the
                # checkpoint subsystem; each commit's digest feeds the
                # meta manifest that resume() verifies against
                files = {}

                def record(path, digest):
                    files[os.path.basename(path)] = {
                        "bytes": digest[0], "crc32": digest[1]}

                record(self.params_path,
                       _ckpt.commit(self.params_path,
                                    self.block.save_parameters))
                if self.trainer is not None:
                    record(self.states_path,
                           _ckpt.atomic_write_bytes(
                               self.states_path,
                               self.trainer.get_states()))
                meta = {"step": step, "extra": self.extra_state,
                        "format_version": _ckpt.FORMAT_VERSION,
                        "files": files}

                def write_meta(tmp):
                    with open(tmp, "w") as f:
                        json.dump(meta, f)
                _ckpt.commit(self.meta_path, write_meta)
                # only now: a failed write above leaves saved False so a
                # later signal/save_now retries instead of silently
                # skipping the one job this class has.  A provisional
                # (possibly torn) fallback save never sets saved -- only
                # a boundary save ends the retry loop.
                if provisional:
                    self._fallback_saved = True
                else:
                    self.saved = True
                if _telemetry._ENABLED:
                    _telemetry.hooks.checkpoint(
                        "save", prefix=self.prefix, step=step,
                        provisional=bool(provisional),
                        signal_triggered=self._signal_seen)
            finally:
                self._saving = False

    def _on_signal(self, signum, frame):
        # Re-entrancy guard: Python delivers a second SIGTERM by
        # running this handler NESTED on the same thread, at an
        # arbitrary bytecode boundary -- possibly while save_now() is
        # mid-commit (save_in_handler, or a signal landing during the
        # boundary save that a `triggered` read started).  Without the
        # guard the nested handler would re-enter save_now through the
        # RLock and interleave a second commit into the first one's
        # tmp-file dance, tearing the provisional save with its own
        # handler.  A re-entrant delivery only records the signal; the
        # outer save already in flight is the one that lands.
        if self._in_handler or self._saving:
            self._signal_seen = True
            if _telemetry._ENABLED:
                _telemetry.hooks.preemption_reentry()
            _chaos.survived("preemption.signal", "reentrant-suppressed")
            return
        self._in_handler = True
        try:
            self._signal_seen = True
            # black box: the preemption is exactly the death a flight
            # recorder exists for -- mark it (with the in-flight trace)
            # and msync so the final seconds survive the SIGKILL that
            # follows the grace window
            _obs.flight.emergency_dump("preemption.signal",
                                       signum=signum,
                                       prefix=self.prefix)
            # chaos: a rule here can deliver a nested signal (callable
            # action invoking _on_signal again) or stall the handler --
            # how tests prove the guard above holds
            _chaos.fail_point("preemption.signal", signum=signum,
                              handler=self)
            if self.save_in_handler:
                self.save_now()
            elif self.fallback_after is not None \
                    and self._fallback_timer is None:
                t = threading.Timer(self.fallback_after, self.save_now,
                                    kwargs={"provisional": True})
                t.daemon = True
                t.start()
                self._fallback_timer = t
        finally:
            self._in_handler = False
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)

    def uninstall(self):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev if prev is not None
                          else signal.SIG_DFL)
        self._prev = {}
        if self._fallback_timer is not None:
            self._fallback_timer.cancel()
            self._fallback_timer = None


def install(prefix=None, block=None, trainer=None,
            signals=(signal.SIGTERM,), extra_state=None,
            save_in_handler=False):
    """Arm SIGTERM-triggered checkpointing; returns the handler.

    With ``prefix=None`` the prefix comes from the
    ``MXNET_CHECKPOINT_ON_SIGTERM`` env var (operator-armed jobs)."""
    if prefix is None:
        from . import env as _env
        prefix = _env.get("MXNET_CHECKPOINT_ON_SIGTERM")
        if not prefix:
            raise MXNetError("preemption.install: no prefix given and "
                             "MXNET_CHECKPOINT_ON_SIGTERM is unset")
    if block is None:
        raise MXNetError("preemption.install needs the block to save")
    return PreemptionHandler(prefix, block, trainer, signals=signals,
                             extra_state=extra_state,
                             save_in_handler=save_in_handler)


def resume(prefix, block, trainer=None, ctx=None):
    """Restore a preemption checkpoint if one exists.

    Returns the saved meta dict (``{"step": ..., "extra": ...}``) or
    None when no checkpoint is present (fresh start).
    """
    params = prefix + "-preempt.params"
    states = prefix + "-preempt.states"
    meta_path = prefix + "-preempt.meta"
    # the meta file commits LAST in save_now: its presence proves the
    # whole checkpoint landed...
    if not os.path.exists(meta_path) or not os.path.exists(params):
        return None
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except ValueError:
        warnings.warn("preemption meta %s is not valid JSON; treating "
                      "as no checkpoint" % meta_path, RuntimeWarning)
        return None
    # ...and its checksums prove the files are the SAME bytes that were
    # committed -- presence alone can't catch bit-rot or a stale params
    # file beside a newer meta.  Metas from before the checkpoint
    # subsystem carry no digests; those keep the legacy presence check.
    files = meta.get("files")
    if files:
        problems = _ckpt.verify_files(os.path.dirname(prefix) or ".",
                                      files)
        if problems:
            warnings.warn(
                "preemption checkpoint %s failed verification (%s); "
                "treating as no checkpoint" % (prefix,
                                               "; ".join(problems)),
                RuntimeWarning)
            return None
    block.load_parameters(params, ctx=ctx)
    if trainer is not None and os.path.exists(states):
        trainer.load_states(states)
    if _telemetry._ENABLED:
        _telemetry.hooks.checkpoint("restore", prefix=prefix,
                                    step=meta.get("step"))
    return meta
