"""Imperative autograd: record / pause / backward / Function.

TPU-native re-design of the reference's C++ tape
(``src/imperative/imperative.cc :: Imperative::RecordOp / Backward``,
Python face ``python/mxnet/autograd.py``).  Design:

- While recording, every op dispatch calls ``jax.vjp`` on its pure compute
  function, storing the residual-holding ``vjp_fn`` on a tape node.  This
  replaces the reference's nnvm ``Gradient`` pass: the backward graph is
  the chain of recorded vjp closures, executed eagerly in reverse
  topological order (gradients themselves are jax arrays, so the whole
  backward still runs async on-device).
- Only arrays reachable from a ``attach_grad()`` leaf are tracked, matching
  the reference's pruning of non-grad paths.
"""
from __future__ import annotations

import threading
from typing import List, Optional

from .base import MXNetError

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    prev = _st().recording
    _state.recording = bool(is_record)
    return prev


def set_training(train_mode):
    prev = _st().training
    _state.training = bool(train_mode)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._is_record = is_record
        self._train = train_mode
        self._prev = None

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._is_record is not None:
            _state.recording = self._is_record
        if self._train is not None:
            _state.training = self._train
        return self

    def __exit__(self, *args):
        _state.recording, _state.training = self._prev


def record(train_mode=True):
    """Scope in which ops are recorded for backward (reference:
    ``autograd.py :: record``)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    """Scope in which recording is suspended (reference: ``pause``)."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


class TapeNode:
    """One recorded op: inputs, vjp closure, per-output cotangent slots."""

    __slots__ = ("inputs", "vjp_fn", "num_outputs", "out_grads", "name",
                 "_out_avals")

    def __init__(self, inputs, vjp_fn, num_outputs, name=""):
        self.inputs = inputs          # list[NDArray] (tracked or leaf)
        self.vjp_fn = vjp_fn          # cotangents -> input cotangents
        self.num_outputs = num_outputs
        self.out_grads: List[Optional[object]] = [None] * num_outputs
        self.name = name
        self._out_avals = []


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference: ``mark_variables``)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._ag_node = None


def _toposort(head_arrays):
    """Reverse-topological order of tape nodes reachable from heads.

    Iterative DFS: tape length is unbounded (e.g. a long imperative RNN
    unroll records thousands of sequential ops), so recursion would hit
    the Python stack limit.
    """
    order = []
    seen = set()
    for arr in head_arrays:
        root = getattr(arr, "_ag_node", None)
        if root is None or id(root) in seen:
            continue
        stack = [(root, False)]
        while stack:
            node, expanded = stack.pop()
            if expanded:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for inp in reversed(node.inputs):
                src = getattr(inp, "_ag_node", None)
                if src is not None and id(src) not in seen:
                    stack.append((src, False))
    return order[::-1]


_LAZY_ADD = None


def _ct_add(a, b):
    """Cotangent accumulation, lazy-aware: pending bulked cotangents add
    inside the queue instead of forcing a flush."""
    from .ndarray import bulk
    if isinstance(a, bulk.LazyData) or isinstance(b, bulk.LazyData):
        global _LAZY_ADD
        if _LAZY_ADD is None:
            import jax as _jax
            _LAZY_ADD = _jax.jit(lambda x, y: x + y)
        return bulk.enqueue(_LAZY_ADD, "ct_add", (a, b))
    return a + b


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run backward from head arrays, accumulating into leaf ``.grad``.

    Reference: ``Imperative::Backward`` (``src/imperative/imperative.cc``);
    grad_req semantics ('write'/'add'/'null') per
    ``include/mxnet/op_attr_types.h :: OpReqType``.
    """
    import jax
    import jax.numpy as jnp
    from .ndarray import NDArray

    def _ones_on(data):
        # seed cotangents ON the head's device, COMMITTED: an
        # uncommitted seed lets linear-op transposes (sum/broadcast take
        # only the cotangent) run on the default device, which may be a
        # remote TPU -- one tunnel round-trip per backward node
        devs = data.devices()
        if len(devs) == 1:
            dev = next(iter(devs))
            with jax.default_device(dev):
                return jax.device_put(jnp.ones(data.shape, data.dtype),
                                      dev)
        return jnp.ones_like(data)

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # Per-backward accumulation buffers: within one backward pass gradients
    # from multiple paths always sum; grad_req only governs how the final
    # sum combines with the existing .grad ('write' replaces, 'add' adds).
    leaf_acc = {}  # id(arr) -> (arr, summed cotangent)

    def _to_leaf(arr, ct):
        if getattr(arr, "_grad_req", "write") == "null":
            return
        key = id(arr)
        if key in leaf_acc:
            leaf_acc[key] = (arr, _ct_add(leaf_acc[key][1], ct))
        else:
            leaf_acc[key] = (arr, ct)

    # Seed cotangents on the producing nodes.
    for h, hg in zip(heads, head_grads):
        node = getattr(h, "_ag_node", None)
        if node is None:
            if getattr(h, "_grad", None) is not None:
                # head is itself a leaf: d head / d head = 1
                g = _ones_on(h._data) if hg is None else hg._data
                _to_leaf(h, g)
                continue
            raise MXNetError(
                "cannot differentiate: array is not part of a recorded "
                "computation (call inside autograd.record())")
        idx = h._ag_out_index
        g = _ones_on(h._data) if hg is None else hg._data
        node.out_grads[idx] = g if node.out_grads[idx] is None \
            else _ct_add(node.out_grads[idx], g)

    for node in _toposort(heads):
        if all(g is None for g in node.out_grads):
            continue
        if node.vjp_fn is None:
            raise MXNetError(
                "backward through a graph that was already freed; pass "
                "retain_graph=True to backward() to allow repeated calls")
        dev = next((next(iter(g.devices())) for g in node.out_grads
                    if g is not None and hasattr(g, "devices")
                    and len(g.devices()) == 1), None)

        def _zeros(shp, dt):
            if dev is not None:
                with jax.default_device(dev):
                    return jax.device_put(jnp.zeros(shp, dt), dev)
            return jnp.zeros(shp, dt)

        cts = tuple(
            g if g is not None else _zeros(shp, dt)
            for g, (shp, dt) in zip(node.out_grads, node._out_avals))
        in_cts = node.vjp_fn(cts if node.num_outputs > 1 else cts[0])
        if not isinstance(in_cts, (tuple, list)):
            in_cts = (in_cts,)
        for inp, ct in zip(node.inputs, in_cts):
            if ct is None:
                continue
            if getattr(ct, "dtype", None) is not None and ct.dtype.name == "float0":
                continue
            src = getattr(inp, "_ag_node", None)
            if src is not None:
                i = inp._ag_out_index
                src.out_grads[i] = ct if src.out_grads[i] is None \
                    else _ct_add(src.out_grads[i], ct)
            elif getattr(inp, "_grad", None) is not None:
                _to_leaf(inp, ct)
        # Cotangent slots always reset (a second backward must not see
        # this pass's partial sums); vjp closures survive only on request.
        node.out_grads = [None] * node.num_outputs
        if not retain_graph:
            node.vjp_fn = None

    for arr, ct in leaf_acc.values():
        if arr._grad_req == "add":
            arr._grad._data = _ct_add(arr._grad._data, ct)
        else:
            arr._grad._data = ct


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Compute and return gradients w.r.t. ``variables`` (reference:
    ``autograd.py :: grad``).  First-order only in this build."""
    from .ndarray import NDArray
    if create_graph:
        raise MXNetError("create_graph=True (higher-order) not supported yet; "
                         "use gluon hybridize + jax.grad composition instead")
    single = isinstance(variables, NDArray)
    vars_ = [variables] if single else list(variables)
    saved = [(v._grad, getattr(v, "_grad_req", "write")) for v in vars_]
    import jax.numpy as jnp
    for v in vars_:
        z = jnp.zeros_like(v._data)
        g = NDArray(z)
        v._grad = g
        v._grad_req = "add"
    backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
    outs = [v._grad for v in vars_]
    for v, (og, oreq) in zip(vars_, saved):
        v._grad = og
        v._grad_req = oreq
    return outs[0] if single else outs


def get_symbol(x):
    raise MXNetError("autograd.get_symbol is not supported: use "
                     "HybridBlock.export / Symbol tracing instead")


class Function:
    """Custom differentiable function with user-defined forward/backward
    (reference: ``autograd.py :: Function``)."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (tuple, list))
        outs = [outputs] if single else list(outputs)
        if is_recording() and any(getattr(i, "_is_tracked", lambda: False)()
                                  for i in inputs if isinstance(i, NDArray)):
            func = self

            def vjp_fn(cts):
                if not isinstance(cts, (tuple, list)):
                    cts = (cts,)
                ct_nd = [NDArray(c) for c in cts]
                with pause():
                    in_grads = func.backward(*ct_nd)
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = (in_grads,)
                return tuple(g._data if isinstance(g, NDArray) else g
                             for g in in_grads)

            node = TapeNode([i for i in inputs if isinstance(i, NDArray)],
                            vjp_fn, len(outs), name=type(self).__name__)
            node._out_avals = [(o.shape, o.dtype) for o in outs]
            for i, o in enumerate(outs):
                o._ag_node = node
                o._ag_out_index = i
        return outs[0] if single else outs
