"""``mx.dataio`` -- the device-feed subsystem (docs/data_pipeline.md).

Overlapped host->device staging for any batch source: a background
thread issues async ``jax.device_put`` through a bounded double buffer
so H2D DMA hides behind training compute, transfers ship compact
dtypes, and a jitted on-device transform expands them after landing
(reference analog: ``iter_prefetcher.h :: PrefetcherIter`` + the C++
decode pipeline's engine-ordered copies).
"""
from .feed import DeviceBatch, DeviceFeed
from .transforms import DeviceTransform

__all__ = ["DeviceBatch", "DeviceFeed", "DeviceTransform"]
