"""Device feed: overlapped host->device staging behind any data source.

The reference hides input cost behind compute with
``iter_prefetcher.h :: PrefetcherIter`` plus engine-ordered copies; the
host-side analogs here (``io.PrefetchingIter``,
``DataLoader._threaded_iter``) only overlap *decode*, so every training
loop still paid a synchronous ``device_put`` per batch on the consumer
thread.  ``DeviceFeed`` moves that transfer off the hot path:

- a background producer thread pulls host batches from the wrapped
  source and issues **async** ``jax.device_put`` (PJRT returns
  immediately; the DMA proceeds while the consumer trains the previous
  batch), through a bounded double buffer (``depth``, default 2);
- batches ship in their COMPACT dtype (uint8 stays uint8 over the
  wire); a jitted :class:`~mxnet_tpu.dataio.transforms.DeviceTransform`
  does cast/normalize/flip/crop after landing;
- with a ``mesh``/``sharding``, staging lands shards directly
  (``jax.make_array_from_process_local_data`` when running
  multi-process, ``device_put`` with the sharding otherwise);
- error/shutdown semantics follow the checkpoint/bulk precedent:
  producer exceptions re-raise at the consumer's next ``next()``,
  ``close()`` joins the thread, ``reset()`` restarts cleanly -- no
  leaked daemon state between epochs.  The producer holds the feed
  only through a *weak* reference while idle/blocked, and a
  ``weakref.finalize`` stops it when the consumer abandons iteration
  mid-epoch without ``close()`` (GC), so a full staging buffer can
  never strand the thread (ISSUE 5 satellite; leak test in
  tests/test_dataio.py).

Telemetry (``feed.*`` instruments, docs/observability.md): producer
busy time, consumer wait, bytes staged, and the per-epoch overlap
fraction ``1 - wait/busy`` -- the library form of the number
``bench_resnet50_e2e`` used to hand-roll.
"""
from __future__ import annotations

import os
import queue
import threading
import time
import weakref

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .. import profiling as _profiling
from .. import sync as _sync
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..context import Context
from ..ndarray import NDArray
from .. import random as _random_mod

__all__ = ["DeviceFeed", "DeviceBatch"]

_END = object()


def _feed_depth(depth):
    if depth is not None:
        return max(1, int(depth))
    return max(1, int(os.environ.get("MXNET_TPU_FEED_DEPTH", "2")))


def _feed_compact(compact):
    if compact is not None:
        return bool(compact)
    return os.environ.get("MXNET_TPU_FEED_COMPACT", "1") != "0"


class DeviceBatch:
    """One device-resident batch yielded by :class:`DeviceFeed`.

    ``arrays`` are post-transform NDArrays on the target device/sharding;
    ``raw`` keeps the staged (pre-transform, compact-dtype) jax arrays so
    callers can retain cheap uint8 slabs and re-expand on device later
    (``DeviceFeed.apply_transform``).  Unpacks like the host loader's
    tuple: ``for x, y in feed`` works.
    """

    __slots__ = ("arrays", "pad", "raw")

    def __init__(self, arrays, pad=0, raw=None):
        self.arrays = tuple(arrays)
        self.pad = pad
        self.raw = raw

    @property
    def data(self):
        return self.arrays[0]

    @property
    def label(self):
        return self.arrays[1] if len(self.arrays) > 1 else None

    def __iter__(self):
        return iter(self.arrays)

    def __getitem__(self, i):
        return self.arrays[i]

    def __len__(self):
        return len(self.arrays)

    def __repr__(self):
        return "DeviceBatch(%s, pad=%d)" % (
            ", ".join("%sx%s" % (a.shape, a.dtype) for a in self.arrays),
            self.pad)


class DeviceFeed:
    """Wrap any batch source into an overlapped device-resident stream.

    ``source`` may be a legacy ``DataIter`` (``.next()`` ->
    ``DataBatch``), an ``ImageIter`` (its ``next_np`` zero-copy path is
    used), a ``gluon.data.DataLoader``, or any iterable/iterator of
    host batches (arrays or tuples of arrays).

    One of ``ctx``/``mesh``/``sharding`` picks the landing placement:
    a :class:`~mxnet_tpu.context.Context` (default: first accelerator,
    else cpu), a ``jax.sharding.Mesh`` (batch axis sharded over
    ``axis_name``), or an explicit ``NamedSharding``.

    The feed is itself an iterator: ``next()`` blocks on the staging
    queue, applies the jitted ``transform`` to the data component, and
    returns a :class:`DeviceBatch`.  ``reset()`` restarts the producer
    (resetting a resettable source) for the next epoch; ``close()``
    joins the thread.
    """

    def __init__(self, source, ctx=None, mesh=None, sharding=None,
                 transform=None, depth=None, compact=None, batch_axis=0,
                 axis_name="dp"):
        self._source = source
        self._depth = _feed_depth(depth)
        self._compact = _feed_compact(compact)
        self.transform = transform
        self._batch_axis = batch_axis
        self._axis_name = axis_name
        self._mesh = mesh
        self._sharding = sharding
        self._device = None
        if sharding is None and mesh is None:
            if ctx is None:
                from ..context import num_tpus, tpu, cpu
                ctx = tpu() if num_tpus() else cpu()
            self._device = ctx.jax_device() if isinstance(ctx, Context) \
                else ctx
        self._queue = None
        self._thread = None
        self._stop = None
        self._error = None
        self._finalizer = None
        # producer busy / consumer wait / bytes staged / batches --
        # always maintained (a few float adds per BATCH, not per op) so
        # overlap_frac() works with telemetry off; mirrored into the
        # feed.* instruments when telemetry is on.  Producer and
        # consumer both write, so every access holds the stats lock.
        self._stats = {"producer_busy": 0.0, "consumer_wait": 0.0,
                       "bytes_staged": 0, "batches": 0}
        self._stats_lock = _sync.Lock(name="feed.stats")
        self._start()

    # -- placement -----------------------------------------------------
    def _placement(self, ndim):
        """Landing target for one staged leaf of rank ``ndim``."""
        if self._sharding is not None:
            return self._sharding
        if self._mesh is not None:
            spec = [None] * ndim
            if ndim:
                spec[self._batch_axis] = self._axis_name
            return NamedSharding(self._mesh, PartitionSpec(*spec))
        return self._device

    def _stage(self, x):
        """Issue the async transfer for one leaf; returns
        ``(device_array, bytes_staged)``."""
        if isinstance(x, NDArray):
            x = x._data
        if isinstance(x, jax.Array):
            target = self._placement(x.ndim)
            if not isinstance(target, NamedSharding) \
                    and target in x.devices():
                return x, 0          # already resident: no re-transfer
            return jax.device_put(x, target), x.nbytes
        x = np.ascontiguousarray(x)
        if self._precast is not None and x.dtype != self._precast:
            # compact staging disabled: pay the cast (and the fat
            # transfer) host-side, mainly for A/B numerics runs
            x = x.astype(self._precast)
        target = self._placement(x.ndim)
        if isinstance(target, NamedSharding):
            # the shared SPMD staging path (parallel.mesh): on a
            # multi-host global mesh the local batch lands as its slice
            # of the global array via make_array_from_process_local_data
            # -- the same pre-sharded batches TrainStep consumes with
            # no re-transfer (docs/distributed.md)
            from ..parallel.mesh import stage_process_local
            return stage_process_local(x, target), x.nbytes
        return jax.device_put(x, target), x.nbytes

    @property
    def _precast(self):
        if self._compact or self.transform is None:
            return None
        return getattr(self.transform, "dtype", None)

    # -- source normalization ------------------------------------------
    def _make_next_batch(self):
        """One-batch step ``() -> (tuple_of_host_arrays, pad)`` closing
        over the *source* only -- never the feed.  The producer derefs
        the feed weakly per batch, so a consumer that abandons
        iteration (GC without close()) releases the thread instead of
        being kept alive by it."""
        src = self._source
        if hasattr(src, "next_np"):          # ImageIter zero-copy path
            def next_batch():
                data, labels, pad = src.next_np()
                return (data, labels), pad
        elif hasattr(src, "next") and hasattr(src, "reset"):  # DataIter
            def next_batch():
                batch = src.next()
                arrays = tuple(batch.data) + tuple(batch.label or ())
                return arrays, getattr(batch, "pad", 0) or 0
        else:
            it = self._src_iter

            def next_batch():
                item = next(it)
                if isinstance(item, (tuple, list)):
                    return tuple(item), 0
                return (item,), 0
        return next_batch

    # -- producer ------------------------------------------------------
    @staticmethod
    def _producer_put(q, stop, item):
        """Blocking put that stays responsive to close()/finalize."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _start(self):
        self._queue = q = queue.Queue(self._depth)
        self._stop = stop = _sync.Event(name="feed.stop")
        self._error = None
        # a plain iterable is consumed through one iterator per epoch
        self._src_iter = iter(self._source) \
            if not (hasattr(self._source, "next_np")
                    or hasattr(self._source, "next")) else None
        next_batch = self._make_next_batch()
        wself = weakref.ref(self)

        def run():
            from .. import chaos as _chaos
            out = _END
            try:
                while not stop.is_set():
                    # chaos fail point on the input path (ISSUE 14):
                    # a seeded sleep rule here stalls the producer so
                    # the goodput ledger's input_wait category must
                    # dominate -- CI's obs stage injects it and gates
                    # that the sentinel names input_wait.  Disarmed:
                    # one module-flag check.
                    _chaos.fail_point("feed.produce")
                    # busy window = host batch production (decode/
                    # batchify) + async transfer issue; the blocking
                    # put below is backpressure, not work, and stays
                    # outside it
                    t0 = time.perf_counter()
                    try:
                        arrays, pad = next_batch()
                    except StopIteration:
                        break
                    feed = wself()
                    if feed is None:         # consumer GC'd mid-epoch
                        return
                    staged, nbytes = [], 0
                    for a in arrays:
                        d, nb = feed._stage(a)
                        staged.append(d)
                        nbytes += nb
                    busy = time.perf_counter() - t0
                    with feed._stats_lock:
                        feed._stats["producer_busy"] += busy
                        feed._stats["bytes_staged"] += nbytes
                        feed._stats["batches"] += 1
                    # drop the strong ref BEFORE the blocking put: while
                    # parked on a full buffer this thread must not be
                    # what keeps the feed alive
                    feed = None
                    if _telemetry._ENABLED:
                        _telemetry.hooks.feed_produce(busy, nbytes)
                    if _profiling._ENABLED:
                        # host->device transfer span on the step
                        # timeline (mx.profiling)
                        from ..profiling import timeline
                        timeline.record("feed.stage", t0, busy,
                                        {"bytes": nbytes})
                    if not DeviceFeed._producer_put(
                            q, stop, (tuple(staged), pad)):
                        return
            except BaseException as e:  # re-raised at consumer next()
                out = e
            DeviceFeed._producer_put(q, stop, out)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="mxnet_tpu.DeviceFeed")
        # GC of an abandoned feed wakes the producer out of a full
        # buffer; close() detaches this and does the full join
        self._finalizer = weakref.finalize(self, _release_producer,
                                           q, stop)
        self._thread.start()

    # -- consumer ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self._error is not None:
            raise self._error
        t0 = time.perf_counter()
        item = self._queue.get()
        wait = time.perf_counter() - t0
        with self._stats_lock:
            self._stats["consumer_wait"] += wait
        if _telemetry._ENABLED:
            _telemetry.hooks.feed_wait(wait)
        if item is _END:
            self._finish_epoch()
            raise StopIteration
        if isinstance(item, BaseException):
            self._error = item
            self._finish_epoch()
            raise item
        staged, pad = item
        arrays = list(staged)
        if self.transform is not None:
            arrays[0] = self.transform(arrays[0], _random_mod.next_key())
        return DeviceBatch([NDArray(a) for a in arrays], pad=pad,
                           raw=staged)

    def _finish_epoch(self):
        th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=10)
        frac = self.overlap_frac()
        if _telemetry._ENABLED:
            _telemetry.hooks.feed_overlap(frac)

    def apply_transform(self, staged):
        """Re-run the jitted transform on a retained raw (compact) device
        array -- lets callers keep uint8 slabs resident and expand per
        use (the bench's staged-epochs pattern)."""
        if self.transform is None:
            return staged
        return self.transform(staged, _random_mod.next_key())

    # -- stats ---------------------------------------------------------
    def stats(self):
        """Copy of the feed counters (seconds / bytes / batches)."""
        with self._stats_lock:
            return dict(self._stats)

    def overlap_frac(self):
        """Share of producer (decode+transfer) time hidden behind
        consumer compute: ``1 - consumer_wait / producer_busy``."""
        with self._stats_lock:
            busy = self._stats["producer_busy"]
            wait = self._stats["consumer_wait"]
        if busy <= 0:
            return 0.0
        return max(0.0, 1.0 - wait / busy)

    # -- lifecycle -----------------------------------------------------
    def reset(self):
        """Stop the in-flight epoch (if any), reset a resettable source,
        and restart the producer for the next epoch."""
        self.close()
        if hasattr(self._source, "reset"):
            self._source.reset()
        elif self._src_iter is not None:
            # a bare iterator cannot be rewound; an iterable can
            try:
                iter(self._source)
            except TypeError:
                raise MXNetError(
                    "DeviceFeed.reset: source is not resettable")
        self._start()

    def close(self):
        """Join the producer thread; idempotent, safe mid-epoch."""
        if self._finalizer is not None:
            self._finalizer.detach()
        if self._stop is not None:
            self._stop.set()
        # drain so a producer blocked on put() wakes promptly
        if self._queue is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _release_producer(q, stop):
    """``weakref.finalize`` callback shared by the staged-feed classes:
    stop the producer of an iterator its consumer abandoned, and drain
    the buffer so a put() parked on a full queue wakes immediately.
    Deliberately holds NO reference to the feed -- that is the point."""
    stop.set()
    try:
        while True:
            q.get_nowait()
    except queue.Empty:
        pass
