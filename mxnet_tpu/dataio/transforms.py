"""Jitted on-device batch transforms for the device feed.

The staging contract (docs/data_pipeline.md): batches cross the
host->device wire in their COMPACT dtype (a uint8 image batch is 4x
smaller than its float32 cast), and the decompression -- cast, scale,
mean/std normalize, random mirror, random crop -- runs as one jitted
XLA program on the device after the batch lands.  The reference does
this work in C++ decode threads before the copy
(``iter_image_recordio_2.cc``); on TPU the arithmetic is effectively
free next to training compute while host->device bandwidth is the
scarce resource, so the split goes the other way.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["DeviceTransform"]


def _chan_const(v, ndim, chan_axis):
    """Broadcastable (1, C, 1, ...) constant from a scalar or per-channel
    sequence, for NCHW-style batches."""
    a = jnp.asarray(np.asarray(v, np.float32))
    if a.ndim == 0 or ndim is None:
        return a
    shape = [1] * ndim
    shape[chan_axis] = a.shape[0]
    return a.reshape(shape)


class DeviceTransform:
    """Compiled post-landing batch transform: ``transform(x, key)``.

    Batches are NCHW (batch, channel, height, width) unless only the
    dtype/scale/normalize stages are used, which are layout-agnostic.
    Stage order: random crop -> random mirror (both on the compact
    dtype) -> cast -> scale -> normalize, so the expensive float math
    happens once, after the cheap integer-domain augmentation.

    ``key`` is a ``jax.random`` PRNG key; it is consumed only when a
    random stage (``rand_mirror``/``crop``) is configured, so a
    deterministic transform compiles to a program that ignores it.
    """

    def __init__(self, dtype="float32", scale=None, mean=None, std=None,
                 rand_mirror=False, crop=None, chan_axis=1):
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.scale = scale
        self.rand_mirror = bool(rand_mirror)
        self.crop = (crop, crop) if isinstance(crop, int) else \
            (tuple(crop) if crop is not None else None)
        self._chan_axis = chan_axis
        self._mean = mean
        self._std = std
        self._fn = jax.jit(self._build())

    def _build(self):
        scale = self.scale
        rand_mirror = self.rand_mirror
        crop = self.crop
        dtype = self.dtype
        chan_axis = self._chan_axis
        mean_v, std_v = self._mean, self._std

        def fn(x, key):
            k_crop, k_mirror = jax.random.split(key)
            if crop is not None:
                ch, cw = crop
                y0 = jax.random.randint(k_crop, (), 0,
                                        x.shape[-2] - ch + 1)
                x0 = jax.random.randint(k_crop, (), 0,
                                        x.shape[-1] - cw + 1)
                starts = [jnp.zeros((), jnp.int32)] * (x.ndim - 2) \
                    + [y0, x0]
                x = jax.lax.dynamic_slice(
                    x, starts, x.shape[:-2] + (ch, cw))
            if rand_mirror:
                flip = jax.random.bernoulli(k_mirror, 0.5, (x.shape[0],))
                flip = flip.reshape((x.shape[0],) + (1,) * (x.ndim - 1))
                x = jnp.where(flip, x[..., ::-1], x)
            if dtype is not None:
                x = x.astype(dtype)
            if scale is not None:
                x = x * jnp.asarray(scale, x.dtype)
            if mean_v is not None:
                x = x - _chan_const(mean_v, x.ndim, chan_axis).astype(x.dtype)
            if std_v is not None:
                x = x / _chan_const(std_v, x.ndim, chan_axis).astype(x.dtype)
            return x

        return fn

    def __call__(self, x, key):
        return self._fn(x, key)
