"""Checkpoint conventions (reference: ``python/mxnet/model.py ::
save_checkpoint/load_checkpoint`` and ``BatchEndParam``).

The on-disk convention is the reference's: ``prefix-symbol.json`` holds
the graph, ``prefix-%04d.params`` holds a single dict with keys
``arg:<name>`` / ``aux:<name>`` in the ``.params`` binary format
(``ndarray.save``), so checkpoints interoperate at the file level.
"""
from __future__ import annotations

from collections import namedtuple

from . import ndarray as nd
from . import symbol as sym

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save graph + parameters for ``epoch`` (reference:
    ``model.py :: save_checkpoint``).  Both files commit atomically
    through mx.checkpoint (tmp+fsync+rename), so a kill mid-save leaves
    the previous epoch's files intact instead of a truncated graph or
    params container."""
    from .checkpoint.core import commit
    if symbol is not None:
        commit("%s-symbol.json" % prefix, symbol.save)
    save_dict = {("arg:%s" % k): v for k, v in (arg_params or {}).items()}
    save_dict.update({("aux:%s" % k): v
                      for k, v in (aux_params or {}).items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    commit(param_name, lambda tmp: nd.save(tmp, save_dict))
    return param_name


def load_params(prefix, epoch):
    """Load just the ``arg:``/``aux:`` dicts of a checkpoint."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:  # bare key (gluon-style file): treat as arg
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Returns ``(symbol, arg_params, aux_params)`` (reference:
    ``model.py :: load_checkpoint``)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
