"""KVStore: the parameter-store API over XLA collectives.

TPU-native re-design of the reference's ``src/kvstore/`` stack
(``kvstore_local.h :: KVStoreLocal``, ``comm.h :: CommDevice``,
``kvstore_dist.h :: KVStoreDist`` + ps-lite, ``kvstore_nccl.h``).

Design (SURVEY.md §5 "Distributed communication backend"):

- ``local`` / ``device`` / ``nccl``: single-process.  There are no
  per-device gradient copies to reduce -- data-parallel gradients live as
  ONE sharded jax.Array whose reduction happened *inside* the compiled
  step via ``psum`` over ICI (see ``mxnet_tpu/parallel``).  Push/pull
  therefore aggregates pushed versions and applies the optimizer, giving
  the reference's ``update_on_kvstore`` semantics without a comm step.
- ``dist_sync`` / ``dist_device_sync`` / ``dist_async``: multi-process.
  ``jax.distributed`` + PJRT replace the ps-lite scheduler/Van.  On the
  TRAINING HOT PATH the dist kvstore is a **veneer over the compiled
  SPMD step** (docs/distributed.md): ``parallel.TrainStep`` over the
  global mesh reduces gradients IN-GRAPH (GSPMD inserts the
  ``all-reduce``; XLA's latency-hiding scheduler overlaps it with
  backprop), so ``push``/``pull`` move ZERO host bytes per step --
  what remains of the kvstore's dist role is the init-time rank-0
  parameter broadcast (``Trainer._sync_initial_params``, one bucketed
  collective) and optimizer-state save/load.  The eager
  ``push``/``pull``/``pushpull`` verbs below still reduce across
  processes (host collectives, bucketed via ``pushpull_bucket``) for
  reference-API compatibility and non-compiled loops.  The
  "server-side optimizer" of the reference (``kvstore_dist_server.h ::
  DataHandleEx``) becomes a replicated update after the allreduce --
  same contract (workers see identical post-update weights), no server
  role needed.
  ``dist_async`` shares this path by DESIGN: the reference's async mode
  exists to hide ps-lite server latency by applying per-worker pushes
  without aggregation (stale weights as the price); with XLA's async
  dispatch the allreduce itself is non-blocking until a sync point, so
  the latency-hiding is already had WITHOUT giving up synchronous
  semantics -- async here means async dispatch, not weight staleness.
- Gradient compression hook mirrors ``gradient_compression.cc`` (2bit with
  error feedback) as a pre-allreduce transform.
"""
from __future__ import annotations

import pickle
import time

import numpy as np

import jax
import jax.numpy as jnp

from . import telemetry as _telemetry
from .base import MXNetError
from .ndarray import NDArray
from .ndarray import sparse as _sp
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _allreduce_across_processes(x):
    """Sum a host-local array across all processes (DCN path): backend
    collectives on multi-process backends (TPU pods), the coordination
    service otherwise (``distributed.host_allreduce``)."""
    from .distributed import host_allreduce, world
    if world()[0] == 1:
        return x
    return host_allreduce(x, average=False)


def _value_nbytes(value):
    """Payload size of a pushed/pulled value from shape/dtype metadata
    only -- never forces a device sync.  Lists sum; sparse and exotic
    values degrade to 0 rather than sync or raise."""
    if isinstance(value, (list, tuple)):
        return sum(_value_nbytes(v) for v in value)
    try:
        shape, dtype = value.shape, value.dtype
        return int(np.prod(shape)) * np.dtype(dtype).itemsize \
            if shape else np.dtype(dtype).itemsize
    except Exception:
        return 0


class _TwoBitCompression:
    """2-bit gradient compression with error feedback (reference:
    ``src/kvstore/gradient_compression.cc``)."""

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residual = {}

    def compress_decompress(self, key, grad):
        r = self._residual.get(key)
        g = grad if r is None else grad + r
        t = self.threshold
        q = jnp.where(g >= t, t, jnp.where(g <= -t, -t, 0.0))
        self._residual[key] = g - q
        return q


class KVStore:
    """Reference: ``include/mxnet/kvstore.h :: KVStore`` /
    ``python/mxnet/kvstore.py``."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}      # key -> NDArray (the "server" copy)
        self._updater = None
        self._optimizer = None
        self._opt_states = {}
        self._compression = None
        self._is_dist = kv_type.startswith("dist")

    # -- topology ------------------------------------------------------
    @property
    def rank(self):
        from .distributed import world
        return world()[1] if self._is_dist else 0

    @property
    def num_workers(self):
        from .distributed import world
        return world()[0] if self._is_dist else 1

    # -- core API ------------------------------------------------------
    def _keyify(self, key):
        return key if isinstance(key, (str, int)) else str(key)

    def init(self, key, value):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        key = self._keyify(key)
        if key in self._store:
            return
        self._store[key] = value.copy() if isinstance(value, NDArray) \
            else NDArray(value)

    def _merge(self, value):
        """Sum a list of pushed values (the reference's CommDevice
        reduce).  All-row-sparse lists merge by row union, staying
        sparse; any dense operand densifies the sum.  Returns a raw
        jnp array for dense results, a sparse array otherwise."""
        if isinstance(value, (list, tuple)):
            if any(isinstance(v, _sp.RowSparseNDArray) for v in value):
                merged = value[0]
                for v in value[1:]:
                    merged = _sp.elemwise_add(merged, v)
                return merged._data if isinstance(merged, NDArray) \
                    else merged
            merged = value[0]._data
            for v in value[1:]:
                merged = merged + v._data
            return merged
        if isinstance(value, _sp.BaseSparseNDArray):
            return value
        return value._data

    def _reduce_for_update(self, key, value):
        """Merge + compress + cross-process reduce one pushed value.
        Returns ``(merged, sparse_grad)``; sparse grads skip compression
        and densify before the dist collective (row unions differ per
        worker; the collective needs a static shape)."""
        merged = self._merge(value)
        sparse_grad = isinstance(merged, _sp.BaseSparseNDArray)
        if not sparse_grad and self._compression is not None:
            merged = self._compression.compress_decompress(key, merged)
        if self._is_dist and sparse_grad:
            merged = merged.todense()._data
            sparse_grad = False
        if self._is_dist:
            merged = _allreduce_across_processes(merged)
        return merged, sparse_grad

    def push(self, key, value, priority=0):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        key = self._keyify(key)
        if key not in self._store:
            raise MXNetError("kvstore key %r not initialized" % key)
        if _telemetry._ENABLED:
            _telemetry.hooks.kv_op("push", _value_nbytes(value))
        merged, sparse_grad = self._reduce_for_update(key, value)
        if self._updater is not None:
            grad = merged if sparse_grad else NDArray(merged)
            self._updater(key, grad, self._store[key])
        else:
            pending = getattr(self, "_pending", None)
            if pending is None:
                self._pending = pending = {}
            if key not in pending:
                pending[key] = merged
            elif sparse_grad or isinstance(pending[key],
                                           _sp.BaseSparseNDArray):
                a, b = pending[key], merged
                a = NDArray(a) if not isinstance(
                    a, (_sp.BaseSparseNDArray, NDArray)) else a
                b = NDArray(b) if not isinstance(
                    b, (_sp.BaseSparseNDArray, NDArray)) else b
                s = _sp.elemwise_add(a, b)
                pending[key] = s if isinstance(
                    s, _sp.BaseSparseNDArray) else s._data
            else:
                pending[key] = pending[key] + merged

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        key = self._keyify(key)
        if key not in self._store:
            raise MXNetError("kvstore key %r not initialized" % key)
        if _telemetry._ENABLED:
            _telemetry.hooks.kv_op("pull", _value_nbytes(self._store[key]))
        pending = getattr(self, "_pending", {})
        if self._updater is None and key in pending:
            src = pending.pop(key)
            if isinstance(src, _sp.BaseSparseNDArray):
                src = src.todense()._data
        else:
            src = self._store[key]._data
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            o._data = src
        return out

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference: ``MXKVStorePushPullEx``).

        Without an optimizer this is allreduce semantics on gradients:
        out <- sum over workers(value).
        """
        if isinstance(key, (list, tuple)):
            outs = out if out is not None else [None] * len(key)
            for k, v, o in zip(key, value, outs):
                self.pushpull(k, v, o, priority)
            return
        key = self._keyify(key)
        # allreduce wall time is DISPATCH time under async XLA; the
        # reduce itself overlaps compute and only lands at a sync point
        t0 = time.perf_counter() if _telemetry._ENABLED else None
        merged, sparse_grad = self._reduce_for_update(key, value)
        if self._updater is not None:
            if key not in self._store:
                raise MXNetError("kvstore key %r not initialized" % key)
            grad = merged if sparse_grad else NDArray(merged)
            self._updater(key, grad, self._store[key])
            result = self._store[key]._data
        else:
            result = merged.todense()._data if sparse_grad else merged
        if t0 is not None:
            _telemetry.hooks.kv_op("pushpull", _value_nbytes(value),
                                   time.perf_counter() - t0)
        if out is not None:
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o in outs:
                o._data = result
        return out

    def pushpull_bucket(self, keys, values, outs, priority=0):
        """Bucketed fused push+pull over a LIST of keys: dense values
        merge per key, coalesce into one flattened buffer per dtype,
        and cross the process boundary in ONE collective
        (``distributed.host_allreduce_bucketed``) instead of one RPC
        per tensor -- the legacy eager path's analog of the compiled
        step's single in-graph all-reduce.  Telemetry records ONE
        ``kvstore.pushpull`` call for the whole bucket (the call-count
        drop ``kv.bytes`` proves).  Keys with sparse gradients or an
        installed updater fall back to per-key :meth:`pushpull`."""
        keys = [self._keyify(k) for k in keys]
        t0 = time.perf_counter() if _telemetry._ENABLED else None
        dense_idx, merged_vals = [], []
        for j, (key, value) in enumerate(zip(keys, values)):
            if self._updater is not None:
                self.pushpull(key, value, outs[j], priority)
                continue
            merged, sparse_grad = self._merge(value), False
            sparse_grad = isinstance(merged, _sp.BaseSparseNDArray)
            if sparse_grad:
                merged = merged.todense()._data
            if self._compression is not None:
                merged = self._compression.compress_decompress(key, merged)
            dense_idx.append(j)
            merged_vals.append(merged)
        if not dense_idx:
            return outs
        from .distributed import world
        if self._is_dist and world()[0] > 1:
            from .distributed import host_allreduce_bucketed
            merged_vals = host_allreduce_bucketed(merged_vals)
        total = 0
        for j, res in zip(dense_idx, merged_vals):
            res = res._data if isinstance(res, NDArray) else res
            total += _value_nbytes(values[j])
            os_ = outs[j] if isinstance(outs[j], (list, tuple)) \
                else [outs[j]]
            for o in os_:
                o._data = res
        if t0 is not None:
            _telemetry.hooks.kv_op("pushpull", total,
                                   time.perf_counter() - t0)
        return outs

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull ONLY the requested rows (reference: ``PullRowSparse``).
        Moves k rows, not the full table: the embedding-scale win the
        row-sparse type exists for.  ``out`` may be a RowSparseNDArray
        (filled sparsely) or a dense NDArray (rows scattered, rest 0);
        with ``out=None`` a RowSparseNDArray is returned."""
        key = self._keyify(key)
        if key not in self._store:
            raise MXNetError("kvstore key %r not initialized" % key)
        if row_ids is None:
            return self.pull(key, out, priority)
        from .distributed import _place
        rows = row_ids._data if isinstance(row_ids, NDArray) else row_ids
        full = self._store[key]._data
        # dedup host-side (reference PullRowSparse dedups): duplicate ids
        # would double rows under the sparse todense() scatter-add.
        # Place the ids WITH the table: an unplaced jnp.asarray would
        # put them on the DEFAULT device (a remote TPU here), dragging
        # the gather through the tunnel per pull.  A DEVICE target, not
        # the table's sharding -- the 1-D id vector can't take a
        # dim-partitioned rank-2 sharding.
        dev = next(iter(full.devices())) \
            if isinstance(full, jax.Array) else None
        rows = _place(np.unique(np.asarray(rows).astype(np.int32)), dev)
        picked_rows = full[rows]                      # (k, ...) gather only
        if out is None:
            return _sp.RowSparseNDArray(picked_rows, rows,
                                        full.shape, full.dtype)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            if isinstance(o, _sp.RowSparseNDArray):
                o._rs_data = picked_rows
                o._rs_indices = rows
            else:
                o._data = jnp.zeros_like(full).at[rows].set(picked_rows)
        return out

    # -- optimizer on the store (reference: server-side optimizer) -----
    def set_optimizer(self, optimizer):
        """Reference: ``KVStore.set_optimizer`` -- pickles the optimizer to
        servers; here it installs the updater on the replicated store."""
        pickled = pickle.dumps(optimizer)  # keep the serialization contract
        self._optimizer = pickle.loads(pickled)
        self._updater = opt.get_updater(self._optimizer)

    def set_gradient_compression(self, compression_params):
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError("unsupported compression type %r" % ctype)
        self._compression = _TwoBitCompression(
            compression_params.get("threshold", 0.5))

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        # atomic tmp+fsync+rename (mx.checkpoint): never a torn .states
        from .checkpoint.core import atomic_write_bytes
        atomic_write_bytes(fname, self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        if self._is_dist:
            from .distributed import barrier
            barrier("kvstore_barrier")


def create(name="local"):
    """Reference: ``kvstore.create``; accepted types: local, device, nccl,
    dist_sync, dist_device_sync, dist_async, dist."""
    if name not in ("local", "device", "nccl", "dist", "dist_sync",
                    "dist_async", "dist_device_sync", "horovod"):
        raise MXNetError("unknown kvstore type %r" % name)
    return KVStore(name)
