"""Inference predictor + ahead-of-time compiled artifacts (reference:
``src/c_api/c_predict_api.cc :: MXPredCreate/SetInput/Forward/GetOutput``
and the ``amalgamation/`` edge-deploy story).

Two deployment levels:

- ``Predictor``: load ``-symbol.json`` + ``.params`` and serve forward
  passes through one jitted program per input-shape class -- the
  ``MXPredCreate`` workflow with XLA as the runtime.
- ``export_compiled`` / ``CompiledPredictor``: the TPU-native "Edge"
  path.  The jitted forward is AOT-lowered and serialized as portable
  StableHLO together with the weights in one archive (``.mxa``), so the
  serving side needs NO model definition code -- the graph, shapes, and
  calling convention travel in the artifact, the analog of the
  reference's amalgamated single-file deploy.
"""
from __future__ import annotations

import json
import os
import zipfile

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray


class Predictor:
    """Reference: the C predict API object (``MXPredCreate``).

    One jitted program is compiled per input-shape *class*; the
    programs live in a bounded LRU (``jit_cache_size``, default
    ``MXNET_TPU_SERVING_PREDICTOR_CACHE``) so a long-lived serving
    process fed adversarial shape diversity cannot grow compiled-
    executable memory without bound -- the least-recently-used shape
    class is dropped (and recompiles if it returns), counted by the
    ``serving.compile_evictions`` telemetry counter.
    """

    def __init__(self, symbol_file, param_file=None, ctx=None,
                 input_shapes=None, jit_cache_size=None):
        from collections import OrderedDict
        from . import symbol as sym_mod
        from .symbol.symbol import _eval_symbol

        self._sym = sym_mod.load(symbol_file) \
            if isinstance(symbol_file, str) \
            else sym_mod.load_json(symbol_file.decode()
                                   if isinstance(symbol_file, bytes)
                                   else symbol_file)
        self._ctx = ctx
        params = {}
        if param_file:
            for k, v in nd.load(param_file).items():
                name = k.split(":", 1)[1] if ":" in k else k
                params[name] = v
        self._params = params
        arg_names = self._sym.list_arguments()
        aux_names = self._sym.list_auxiliary_states()
        self._input_names = [n for n in arg_names
                             if n not in params and n not in aux_names]
        if input_shapes:
            missing = [n for n in input_shapes if n not in arg_names]
            if missing:
                raise MXNetError("unknown inputs %r" % missing)
        self._input_shapes = dict(input_shapes or {})
        self._inputs = {}
        self._outputs = None
        if jit_cache_size is None:
            from . import env as _env
            jit_cache_size = _env.get("MXNET_TPU_SERVING_PREDICTOR_CACHE")
        self._jit_cache_size = max(1, int(jit_cache_size))
        self._jit_cache = OrderedDict()   # shape key -> jitted program

        def pure(feed_vals):
            class _W:
                __slots__ = ("_data",)

                def __init__(self, d):
                    self._data = d
            feed = {k: _W(v) for k, v in feed_vals.items()}
            outs = _eval_symbol(self._sym, feed)
            return tuple(o._data for o in outs)

        self._pure = pure

    def _jit_for(self, feed):
        """The jitted program for this input-shape class, LRU-bounded.
        A FRESH ``jax.jit`` wrapper per shape class means evicting the
        entry releases its compiled executable (one shared wrapper
        would keep every shape's program alive in jax's own cache)."""
        import jax
        key = tuple(sorted((name, tuple(v._data.shape),
                            str(v._data.dtype))
                           for name, v in self._inputs.items()))
        cache = self._jit_cache
        fn = cache.get(key)
        if fn is None:
            fn = jax.jit(self._pure)
            cache[key] = fn
            if len(cache) > self._jit_cache_size:
                cache.popitem(last=False)
                from . import telemetry as _telemetry
                if _telemetry._ENABLED:
                    _telemetry.hooks.serving_evict()
        else:
            cache.move_to_end(key)
        return fn

    def set_input(self, name, arr):
        """Reference: ``MXPredSetInput``."""
        if name not in self._input_names:
            raise MXNetError("unknown input %r (inputs: %s)"
                             % (name, self._input_names))
        self._inputs[name] = arr if isinstance(arr, NDArray) \
            else nd.array(np.asarray(arr), ctx=self._ctx)

    def forward(self, **kwargs):
        """Reference: ``MXPredForward``."""
        for k, v in kwargs.items():
            self.set_input(k, v)
        missing = [n for n in self._input_names if n not in self._inputs]
        if missing:
            raise MXNetError("inputs not set: %r" % missing)
        feed = {k: v._data for k, v in self._params.items()}
        feed.update({k: v._data for k, v in self._inputs.items()})
        missing_aux = [n for n in self._sym.list_auxiliary_states()
                       if n not in feed]
        if missing_aux:
            feed.update(self._default_aux(missing_aux))
        self._outputs = [NDArray(o) for o in self._jit_for(feed)(feed)]
        return self._outputs

    def _default_aux(self, names):
        """Default values for aux states absent from the checkpoint
        (zeros; ones for variances).  Shapes come from ONE graph shape
        inference, cached -- this sits on the serving hot path."""
        cache = getattr(self, "_aux_cache", None)
        if cache is None:
            shapes = {n: v.shape for n, v in self._params.items()}
            shapes.update({n: v.shape for n, v in self._inputs.items()})
            shapes.update(self._input_shapes)
            _, _, aux_shapes = self._sym.infer_shape(**{
                k: shapes[k] for k in self._sym.list_arguments()
                if k in shapes})
            aux_names = self._sym.list_auxiliary_states()
            cache = {
                n: np.full(s, 1.0 if n.endswith("var") else 0.0,
                           np.float32)
                for n, s in zip(aux_names, aux_shapes)}
            self._aux_cache = cache
        return {n: cache[n] for n in names}

    def get_output(self, index=0):
        """Reference: ``MXPredGetOutput``."""
        if self._outputs is None:
            raise MXNetError("call forward() first")
        return self._outputs[index]

    @property
    def output_count(self):
        return len(self._sym._outputs)


# ----------------------------------------------------------------------
# AOT-compiled artifacts ("Edge" deploy)
# ----------------------------------------------------------------------

_MXA_VERSION = 1


def export_compiled(block, path, input_shapes, dtype="float32"):
    """AOT-compile a HybridBlock's forward and write a self-contained
    ``.mxa`` archive: serialized StableHLO + weights + calling
    convention.  Loading needs no model code (``CompiledPredictor``).
    """
    import jax
    from jax import export as jexport

    if not hasattr(block, "functionalize"):
        raise MXNetError("export_compiled expects a HybridBlock")
    shapes = [tuple(s) for s in input_shapes]
    if any(p._data is None for p in block._all_params()):
        # materialize deferred params with one probe forward, on the
        # SAME device (and dtype) the materialized params use
        ctx = next((p.data().context for p in block._all_params()
                    if p._data is not None), None)
        probe = [nd.zeros(s, ctx=ctx).astype(dtype) for s in shapes]
        block(*probe)
    pure_fn, pnames, pmap = block.functionalize(training=False)
    pvals = {n: pmap[n]._data._data for n in pnames}
    key = jax.random.PRNGKey(0)

    def fn(pvals, *xs):
        outs, _aux = pure_fn(pvals, list(xs), key)
        return tuple(outs)

    specs = [jax.ShapeDtypeStruct(s, np.dtype(dtype)) for s in shapes]
    pspecs = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
              for n, v in pvals.items()}
    # multi-platform artifact: the same .mxa serves on TPU and CPU
    # (edge deploys rarely run where they were built)
    # no donation: this is the AOT inference export -- the serving
    # runtime feeds the same weight buffers into every request
    exported = jexport.export(jax.jit(fn),  # mxlint: disable=undonated-train-state
                              platforms=("cpu", "tpu"))(pspecs, *specs)
    hlo = exported.serialize()

    # weights in the reference .params container
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".params",
                                     delete=False) as tf:
        nd.save(tf.name, {n: NDArray(v) for n, v in pvals.items()})
        with open(tf.name, "rb") as f:
            param_bytes = f.read()
    os.unlink(tf.name)

    meta = {
        "version": _MXA_VERSION,
        "input_shapes": [list(s) for s in shapes],
        "input_dtype": str(dtype),
        "param_names": list(pvals),
        "num_outputs": len(exported.out_avals),
    }
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("meta.json", json.dumps(meta))
        z.writestr("forward.stablehlo", hlo)
        z.writestr("weights.params", param_bytes)
    return path


class CompiledPredictor:
    """Serve a ``.mxa`` artifact (reference: the edge predict ABI).  The
    StableHLO program is deserialized and executed by XLA directly; no
    model definition or Python graph code is involved."""

    def __init__(self, path):
        import tempfile
        from jax import export as jexport
        with zipfile.ZipFile(path) as z:
            self.meta = json.loads(z.read("meta.json"))
            self._exported = jexport.deserialize(
                z.read("forward.stablehlo"))
            with tempfile.NamedTemporaryFile(suffix=".params",
                                             delete=False) as tf:
                tf.write(z.read("weights.params"))
                pfile = tf.name
        params = nd.load(pfile)
        os.unlink(pfile)
        self._pvals = {n: v._data for n, v in params.items()}

    def forward(self, *inputs):
        vals = [i._data if isinstance(i, NDArray) else np.asarray(i)
                for i in inputs]
        outs = self._exported.call(self._pvals, *vals)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        return [NDArray(o) for o in outs]

    __call__ = forward


class NativePredictor:
    """Python handle to the C edge-predict runtime (reference:
    ``c_predict_api.h`` workflow).  The runtime itself
    (``_native/predict_native.cc``) is a dependency-free C++ interpreter
    over exported ONNX artifacts with a flat C ABI -- usable from any
    language with no Python; this class is the convenience binding for
    tests and Python callers.
    """

    def __init__(self, onnx_path):
        import ctypes
        from ._native import load_predict
        lib = load_predict()
        if lib is None:
            raise MXNetError("native predict runtime unavailable "
                             "(no C++ toolchain?)")
        self._lib = lib
        self._h = ctypes.c_void_p()
        rc = lib.MXPredCreateFromFile(str(onnx_path).encode(),
                                      ctypes.byref(self._h))
        if rc != 0:
            raise MXNetError("MXPredCreate failed: %s"
                             % lib.MXPredGetLastError().decode())

    def forward(self, data, input_name=None):
        import ctypes
        import numpy as _np
        lib = self._lib
        a = _np.ascontiguousarray(_np.asarray(
            data.asnumpy() if hasattr(data, "asnumpy") else data,
            _np.float32))
        shape = (ctypes.c_int64 * a.ndim)(*a.shape)
        rc = lib.MXPredSetInput(
            self._h, input_name.encode() if input_name else None,
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape,
            a.ndim)
        if rc == 0:
            rc = lib.MXPredForward(self._h)
        if rc != 0:
            raise MXNetError("MXPredForward failed: %s"
                             % lib.MXPredGetLastError().decode())
        # two-step query: rank first (shape=NULL), then the dims
        ndim = ctypes.c_int()
        lib.MXPredGetOutputShape(self._h, 0, None, ctypes.byref(ndim))
        oshape = (ctypes.c_int64 * max(ndim.value, 1))()
        lib.MXPredGetOutputShape(self._h, 0, oshape, ctypes.byref(ndim))
        shp = tuple(oshape[i] for i in range(ndim.value))
        out = _np.empty(shp, _np.float32)
        rc = lib.MXPredGetOutput(
            self._h, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.size)
        if rc != 0:
            raise MXNetError("MXPredGetOutput failed: %s"
                             % lib.MXPredGetLastError().decode())
        return out

    def close(self):
        if getattr(self, "_h", None):
            self._lib.MXPredFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
