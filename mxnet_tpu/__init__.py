"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new design (not a port) of the reference ``MXNetEdge/incubator-mxnet``
per ``SURVEY.md``: imperative NDArray + per-op autograd, Gluon blocks with
``hybridize()`` -> XLA jit, KVStore over ICI/DCN collectives, RecordIO data
pipeline.  Compute substrate: JAX/XLA/PJRT.

Typical use mirrors the reference::

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    x = mx.nd.ones((2, 3), ctx=mx.tpu())
"""
__version__ = "0.1.0"

import os as _os

# Persistent XLA compilation cache: the imperative NDArray surface compiles
# one tiny XLA program per (op, shape) pair; caching them on disk makes
# every process after the first start hot.  (The reference's analog is
# cuDNN autotune caching, MXNET_CUDNN_AUTOTUNE_DEFAULT.)
if _os.environ.get("MXNET_TPU_COMPILATION_CACHE", "1") != "0":
    import jax as _jax

    def _cache_fingerprint():
        # AOT artifacts are only valid for the exact compiler build and
        # host ISA that produced them.  A home directory shared across
        # machines (or across a rolling libtpu upgrade) serving stale
        # executables is a startup SIGILL / libtpu-version-mismatch
        # crash, not a warm start -- so the cache dir is keyed on
        # jax/jaxlib/libtpu versions plus the host CPU model+flags.
        import hashlib
        import platform as _plat
        parts = [_jax.__version__, _plat.machine()]
        try:
            import jaxlib as _jaxlib
            parts.append(getattr(_jaxlib, "__version__", ""))
        except Exception:
            pass
        from importlib import metadata as _md
        for _pkg in ("libtpu", "libtpu-nightly"):
            try:
                parts.append(_pkg + "=" + _md.version(_pkg))
            except Exception:
                pass
        try:
            model = flags = ""
            with open("/proc/cpuinfo") as _f:
                for _line in _f:
                    if not model and _line.startswith("model name"):
                        model = _line.strip()
                    elif not flags and _line.startswith("flags"):
                        flags = _line.strip()
                    if model and flags:
                        break
            parts += [model, flags]
        except OSError:
            pass
        return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]

    _cache_dir = _os.environ.get("MXNET_TPU_COMPILATION_CACHE_DIR")
    if _cache_dir is None:
        _cache_root = _os.path.expanduser("~/.cache/mxnet_tpu/xla")
        _cache_dir = _os.path.join(_cache_root, _cache_fingerprint())
        # best-effort GC: prune sibling fingerprint dirs untouched for
        # 30+ days (each rolling jaxlib/libtpu bump orphans one).  Every
        # import touches its OWN dir's mtime first, so a cache that is
        # still in use anywhere (even read-only warm) stays fresh as
        # long as its processes restart within the window.
        try:
            import shutil as _shutil
            import time as _time
            if _os.path.isdir(_cache_dir):
                _os.utime(_cache_dir, None)
            _cutoff = _time.time() - 30 * 86400
            for _d in _os.listdir(_cache_root):
                _p = _os.path.join(_cache_root, _d)
                if (_p != _cache_dir and len(_d) == 16
                        and _os.path.isdir(_p)
                        and _os.path.getmtime(_p) < _cutoff):
                    _shutil.rmtree(_p, ignore_errors=True)
        except OSError:
            pass
    try:
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:
        pass

# Transfer guard (sharding sanitizer runtime wiring): with
# MXNET_TPU_TRANSFER_GUARD=disallow, an IMPLICIT host<->device transfer
# inside the step -- a Python scalar leaking into dispatch, an un-placed
# index array -- raises at the transfer instead of silently stalling the
# pipeline behind a device round-trip every iteration.  Applied before
# any framework dispatch so import-time ops are covered too; a bad mode
# string fails loudly here (jax names the valid options).  Scoped use:
# mxnet_tpu.analysis.sharding.transfer_guard(mode).  docs/sharding.md.
_transfer_guard_mode = _os.environ.get("MXNET_TPU_TRANSFER_GUARD", "")
if _transfer_guard_mode:
    import jax as _jax_guard
    _jax_guard.config.update("jax_transfer_guard", _transfer_guard_mode)

from . import base
from .base import MXNetError
from . import sync
from . import telemetry
from . import obs
from . import chaos
from .context import (Context, cpu, cpu_pinned, current_context, gpu,
                      num_gpus, num_tpus, tpu)
from . import engine
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd
from . import initializer
from . import initializer as init
from . import metric
from . import optimizer
from .optimizer import lr_scheduler
from . import symbol
from . import symbol as sym
from . import executor
from .executor import Executor
from . import gluon
from . import kvstore
from . import kvstore as kv
from . import recordio
from . import io
from . import image
from . import dataio
from . import parallel
from . import amp
from . import model
from . import callback
from . import module
from . import module as mod
from . import profiler
from . import profiling
from . import kernels
from . import bucketing
from . import runtime
from .distributed import distributed_init
from . import numpy as np
from . import numpy_extension as npx
from . import predictor
from .predictor import Predictor, CompiledPredictor
from . import serving
from . import visualization as viz
visualization = viz
from . import onnx
from . import contrib
from . import env
from . import checkpoint
from . import preemption
from . import horovod
from . import analysis
from . import name
from . import attribute
from .attribute import AttrScope
from .optimizer import lr_scheduler as lr_scheduler
from . import test_utils
