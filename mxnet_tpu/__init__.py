"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new design (not a port) of the reference ``MXNetEdge/incubator-mxnet``
per ``SURVEY.md``: imperative NDArray + per-op autograd, Gluon blocks with
``hybridize()`` -> XLA jit, KVStore over ICI/DCN collectives, RecordIO data
pipeline, AMP, Pallas fused kernels.  Compute substrate: JAX/XLA/PJRT.

Typical use mirrors the reference::

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    x = mx.nd.ones((2, 3), ctx=mx.tpu())
"""
__version__ = "0.1.0"

from . import base
from .base import MXNetError
from .context import (Context, cpu, cpu_pinned, current_context, gpu,
                      num_gpus, num_tpus, tpu)
from . import engine
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd
