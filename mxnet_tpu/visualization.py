"""Network visualization (reference: ``python/mxnet/visualization.py ::
print_summary, plot_network``)."""
from __future__ import annotations

import numpy as np

from .base import MXNetError


def _node_shapes(symbol, shape):
    """Per-node output shapes + variable shapes from ONE inference pass
    over the whole graph (O(N), not per-node)."""
    var_shapes, out_by_node = {}, {}
    if not shape:
        return var_shapes, out_by_node
    arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
    names = symbol.list_arguments() + symbol.list_auxiliary_states()
    var_shapes = {n: s for n, s in
                  zip(names, list(arg_shapes) + list(aux_shapes)) if s}
    internals = symbol.get_internals()
    _, out_shapes, _ = internals.infer_shape_partial(**shape)
    for (node, idx), s in zip(internals._outputs, out_shapes):
        if idx == 0 and s is not None:
            out_by_node[id(node)] = tuple(s)
    return var_shapes, out_by_node


def print_summary(symbol, shape=None, line_length=120):
    """Print a layer table: op, name, output shape, param count, inputs
    (reference: ``mx.viz.print_summary``).  ``shape`` maps input names
    to shapes so output shapes can be inferred.  Parameter counts cover
    learnable variables only (inputs, labels, and aux states such as
    BatchNorm running stats are excluded, matching collect_params)."""
    nodes = symbol._topo()
    var_shapes, out_by_node = _node_shapes(symbol, shape)
    aux = set(symbol.list_auxiliary_states())

    def n_params(node):
        if node.op is not None:
            return 0
        if shape and node.name in shape:
            return 0                      # graph inputs
        if node.name in aux or node.name.endswith("_label"):
            return 0                      # aux states / labels
        s = var_shapes.get(node.name)
        return int(np.prod(s)) if s else 0

    header = ("%-28s %-20s %-20s %-12s %s"
              % ("Layer (type)", "Name", "Output Shape", "Params",
                 "Previous"))
    print("=" * line_length)
    print(header)
    print("=" * line_length)
    total = 0
    for node in nodes:
        kind = node.op or "Variable"
        prev = ",".join(src.name for src, _ in node.inputs)[:40]
        os_ = var_shapes.get(node.name) if node.op is None \
            else out_by_node.get(id(node))
        p = n_params(node)
        total += p
        print("%-28s %-20s %-20s %-12d %s"
              % (kind[:28], node.name[:20],
                 str(tuple(os_)) if os_ else "?", p, prev))
    print("=" * line_length)
    print("Total params: {:,}".format(total))
    return total


def plot_network(symbol, title="plot", shape=None, save_format="pdf",
                 node_attrs=None):
    """Graphviz rendering of the graph (reference: ``plot_network``).
    Requires the ``graphviz`` package; ``shape`` adds output-shape
    labels, ``node_attrs`` merges into every node's attributes."""
    try:
        import graphviz
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the graphviz package (not available "
            "in this environment); use print_summary instead") from e
    var_shapes, out_by_node = _node_shapes(symbol, shape)
    attrs = dict(node_attrs or {})
    dot = graphviz.Digraph(name=title, format=save_format)
    nodes = symbol._topo()
    for node in nodes:
        s = var_shapes.get(node.name) if node.op is None \
            else out_by_node.get(id(node))
        suffix = "\n%s" % (tuple(s),) if s else ""
        if node.op is None:
            dot.node(node.name, node.name + suffix, shape="oval",
                     fillcolor="#8dd3c7", style="filled", **attrs)
        else:
            dot.node(node.name,
                     "%s\n%s%s" % (node.op, node.name, suffix),
                     shape="box", fillcolor="#fb8072", style="filled",
                     **attrs)
    for node in nodes:
        for src, _ in node.inputs:
            dot.edge(src.name, node.name)
    return dot
