"""Streaming evaluation metrics (reference: ``python/mxnet/metric.py``)."""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ndarray import NDArray

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    key = str(metric).lower()
    if key == "acc":
        key = "accuracy"
    if key == "ce":
        key = "crossentropy"
    if key not in _METRIC_REGISTRY:
        raise MXNetError("unknown metric %r" % metric)
    return _METRIC_REGISTRY[key](*args, **kwargs)


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def reset_local(self):
        """Reset the rolling window (reference keeps global vs local
        stats; here the two coincide)."""
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        """``(name, value)`` reduced across every process of a
        multi-host run: local ``(sum_metric, num_inst)`` pairs ride ONE
        bucketed host collective (the metric-reduction survivor of the
        one-program SPMD contract, docs/distributed.md) -- never a
        per-metric RPC.  Single-process this is :meth:`get`."""
        from .distributed import host_allreduce_bucketed, world
        if world()[0] == 1:
            return self.get()
        import numpy as np
        stats = np.asarray([float(self.sum_metric),
                            float(self.num_inst)], np.float64)
        total = np.asarray(host_allreduce_bucketed([stats])[0])
        if total[1] == 0:
            return (self.name, float("nan"))
        return (self.name, float(total[0] / total[1]))

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())


def _listify(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(np.int64).ravel()
            label = label.astype(np.int64).ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__("%s_%d" % (name, top_k), **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_np(label).astype(np.int64).ravel()
            pred = _as_np(pred)
            topk = np.argsort(-pred, axis=-1)[:, :self.top_k]
            self.sum_metric += (topk == label[:, None]).any(-1).sum()
            self.num_inst += len(label)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            self.sum_metric += ((label.reshape(pred.shape) - pred) ** 2).mean() \
                * len(label)
            self.num_inst += len(label)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            self.sum_metric += np.abs(label.reshape(pred.shape) - pred).mean() \
                * len(label)
            self.num_inst += len(label)


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            self.sum_metric += np.sqrt(
                ((label.reshape(pred.shape) - pred) ** 2).mean()) * len(label)
            self.num_inst += len(label)


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_np(label).ravel().astype(np.int64)
            pred = _as_np(pred)
            prob = pred[np.arange(label.shape[0]), label]
            self.sum_metric += (-np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_np(label).ravel().astype(np.int64)
            pred = _as_np(pred).reshape(-1, _as_np(pred).shape[-1])
            prob = pred[np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = prob[~ignore]
            self.sum_metric += (-np.log(prob + self.eps)).sum()
            self.num_inst += prob.shape[0]

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(np.exp(self.sum_metric / self.num_inst)))


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_np(label).ravel().astype(np.int64)
            pred = _as_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(-1)
            pred = pred.ravel().astype(np.int64)
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += 1

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1)
        rec = self.tp / max(self.tp + self.fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return (self.name, f1)


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _listify(preds):
            loss = _as_np(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False):
        super().__init__(name)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_listify(labels), _listify(preds)):
            v = self._feval(_as_np(label), _as_np(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1


def np_metric(fn, name=None):
    return CustomMetric(fn, name or fn.__name__)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return (names, values)
