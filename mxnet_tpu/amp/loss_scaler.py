"""Dynamic loss scaling for fp16 AMP.

Reference: ``python/mxnet/contrib/amp/loss_scaler.py :: LossScaler`` --
scale doubles every ``scale_window`` clean steps, halves on overflow.
bfloat16 shares fp32's exponent range, so bf16 mode does not need
scaling; this exists for fp16 parity and for users porting fp16 recipes.

Overflow detection (ISSUE 16 satellite) shares the numerics sentinel's
fused reduction: ONE jitted finite-check over the bucketed gradient set
(``analysis.numerics.finite_all``) and ONE boolean device_get per step,
timed into the ``dispatch.host_sync_time`` ledger (kind
``amp.overflow_check``) -- not a host round-trip per gradient array.
"""
from __future__ import annotations


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, min_scale=1.0):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._min_scale = float(min_scale)
        self._unskipped = 0

    def has_overflow(self, grad_arrays):
        """True if any gradient contains inf/nan (reference:
        ``multi_all_finite``): one fused jitted check over the bucketed
        gradient set, one device_get, one ``host_sync`` timer sample."""
        import time

        import numpy as np

        from ..analysis import numerics as _numerics
        from .. import telemetry as _telemetry
        grads = [g for g in grad_arrays if g is not None]
        if not grads:
            return False
        ok_dev = _numerics.finite_all(grads)
        t0 = time.perf_counter()
        ok = bool(np.asarray(ok_dev))
        if _telemetry._ENABLED:
            _telemetry.hooks.host_sync("amp.overflow_check",
                                       time.perf_counter() - t0)
        return not ok

    def update_scale(self, overflow):
        """Adjust after a step (reference: ``LossScaler.update_scale``)."""
        from .. import telemetry as _telemetry
        if overflow:
            before = self.loss_scale
            self.loss_scale = max(self._min_scale,
                                  self.loss_scale / self._scale_factor)
            self._unskipped = 0
            if _telemetry._ENABLED:
                _telemetry.hooks.amp_overflow(before, self.loss_scale)
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                before = self.loss_scale
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
                if _telemetry._ENABLED:
                    _telemetry.hooks.amp_rescale(before, self.loss_scale)
