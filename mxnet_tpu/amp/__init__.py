"""Automatic mixed precision.

TPU-native re-design of the reference AMP
(``python/mxnet/contrib/amp/amp.py :: init, init_trainer, scale_loss,
convert_hybrid_block``).  The reference monkey-patches every generated op
wrapper to insert casts; here every op dispatch -- eager AND inside
hybridize/TrainStep traces -- flows through ``ndarray.invoke``, so AMP is
one policy hook at that chokepoint, driven by the same three cast lists
(``amp/lists.py``).

Design (bf16-first):

- ``target_dtype='bfloat16'`` (default): parameters stay fp32; inputs of
  MXU-bound ops (conv/matmul) are cast to bf16 at the op boundary, and the
  cast's vjp returns fp32 gradients -- fp32 master weights for free, no
  loss scaling needed (bf16 keeps fp32's exponent).  This is the standard
  TPU mixed-precision recipe.
- ``target_dtype='float16'``: same casting, plus ``LossScaler`` dynamic
  loss scaling wired into ``Trainer`` via ``init_trainer``/``scale_loss``
  (reference semantics: skip the update on overflow, scale *= 2 every 2k
  clean steps, /= 2 on overflow).
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

import jax.numpy as jnp

from ..base import MXNetError
from . import lists
from .loss_scaler import LossScaler

__all__ = ["init", "scope", "is_active", "target_dtype", "init_trainer",
           "scale_loss", "unscale", "convert_hybrid_block", "LossScaler",
           "policy_token", "apply_op_casts", "lists"]

_state = threading.local()


def _st():
    if not hasattr(_state, "dtype"):
        _state.dtype = None
    return _state


def init(target_dtype="bfloat16"):
    """Globally activate mixed precision (reference: ``amp.init``)."""
    td = np.dtype(jnp.bfloat16.dtype) if str(target_dtype) == "bfloat16" \
        else np.dtype(target_dtype)
    if td not in (np.dtype(jnp.bfloat16.dtype), np.dtype(np.float16)):
        raise MXNetError("amp target_dtype must be bfloat16 or float16, "
                         "got %r" % (target_dtype,))
    _st().dtype = td


def shutdown():
    """Deactivate AMP (not in the reference; kept for test/bench hygiene)."""
    _st().dtype = None


@contextlib.contextmanager
def scope(target_dtype="bfloat16"):
    """Scoped AMP activation (TPU-native convenience)."""
    prev = _st().dtype
    init(target_dtype)
    try:
        yield
    finally:
        _state.dtype = prev


def is_active():
    return _st().dtype is not None


def target_dtype():
    return _st().dtype


def policy_token():
    """Hashable token for jit-cache keys (hybridize / TrainStep)."""
    d = _st().dtype
    return str(d) if d is not None else None


_TARGET_OPS = frozenset(lists.TARGET_DTYPE_OPS)
_FP32_OPS = frozenset(lists.FP32_OPS)
_WIDEST_OPS = frozenset(lists.WIDEST_TYPE_CASTS)
_F32 = np.dtype(np.float32)


def _is_float(d):
    return d in (_F32, np.dtype(np.float16), np.dtype(jnp.bfloat16.dtype))


def apply_op_casts(op_name, datas):
    """Cast an op's tensor inputs per the active policy.  Called from
    ``ndarray.invoke`` (the one dispatch chokepoint)."""
    td = _st().dtype
    if td is None:
        return datas
    if op_name in _TARGET_OPS:
        return [d if d is None or not _is_float(np.dtype(d.dtype))
                else d.astype(td) for d in datas]
    if op_name in _FP32_OPS:
        return [d if d is None or not _is_float(np.dtype(d.dtype))
                else d.astype(_F32) for d in datas]
    if op_name in _WIDEST_OPS:
        dts = [np.dtype(d.dtype) for d in datas if d is not None]
        if any(dt == _F32 for dt in dts) and \
                any(_is_float(dt) and dt != _F32 for dt in dts):
            return [d if d is None or not _is_float(np.dtype(d.dtype))
                    else d.astype(_F32) for d in datas]
    return datas


# ----------------------------------------------------------------------
# Trainer integration (fp16 loss scaling; reference amp.py semantics)
# ----------------------------------------------------------------------

def init_trainer(trainer, loss_scaler=None):
    """Attach dynamic loss scaling to a Trainer (reference:
    ``amp.init_trainer``)."""
    trainer._amp_loss_scaler = loss_scaler or LossScaler()
    return trainer


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Yield the scaled loss for backward (reference: ``amp.scale_loss``)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    if isinstance(loss, (list, tuple)):
        yield type(loss)(l * scaler.loss_scale for l in loss)
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Divide accumulated gradients by the current loss scale (reference:
    ``amp.unscale``).  Marks the trainer so ``step()`` does not divide a
    second time."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        g = p.grad_or_none
        if g is not None:
            g._data = g._data * inv
    trainer._amp_unscaled = True


def convert_hybrid_block(block, target_dtype="bfloat16", ctx=None):
    """Return the block configured for mixed-precision inference/training
    (reference: ``amp.convert_hybrid_block`` rewrites the symbol graph;
    here activation is the dispatch policy, so conversion = activate +
    drop stale compiled entries)."""
    init(target_dtype)
    def _clear(b):
        if hasattr(b, "_cached_entries"):
            object.__setattr__(b, "_cached_entries", {})
        for c in b._children.values():
            _clear(c)
    _clear(block)
    return block
