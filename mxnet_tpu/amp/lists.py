"""AMP cast lists over the op registry.

Reference: ``python/mxnet/contrib/amp/lists/symbol_fp16.py :: FP16_FUNCS,
FP32_FUNCS, WIDEST_TYPE_CASTS``.  The reference enumerates every generated
op; here the lists name registry ops and everything unlisted runs in
whatever dtype its inputs already have (cast-through), which matches the
reference's FP16_FP32_FUNCS behavior.

TPU note: the target dtype is bfloat16 by default -- the MXU's native
input type -- and the FP32 list keeps reductions/normalizations/losses in
fp32 for range safety (bf16 has fp32's exponent, so this list is shorter
than the reference's fp16 one; it is kept for fp16 mode and for
reduction accuracy).
"""

# Ops whose FLOPs dominate and map onto the MXU: run in the target dtype.
TARGET_DTYPE_OPS = [
    "FullyConnected",
    "Convolution",
    "Deconvolution",
    "dot",
    "batch_dot",
    "matmul",
    "einsum",
    "tensordot",
    "RNN",
]

# Ops kept in float32 for accumulation range/precision (reference
# FP32_FUNCS core; softmax/losses).  BatchNorm/LayerNorm are NOT here:
# their kernels accumulate stats in fp32 internally while activations
# stay in the compute dtype (ops/nn.py), which saves two full-tensor
# casts per normalization.
FP32_OPS = [
    "L2Normalization",
    "softmax",
    "log_softmax",
    "SoftmaxActivation",
    "SoftmaxOutput",
    "norm",
    "mean",
    "sum",
    "prod",
    "_np_var",
    "_np_std",
    "exp",
    "log",
    "log2",
    "log10",
    "log1p",
    "expm1",
    "erf",
    "erfinv",
    "gamma",
    "gammaln",
    "smooth_l1",
    "MakeLoss",
    "LinearRegressionOutput",
    "LogisticRegressionOutput",
    "MAERegressionOutput",
]

# Elementwise multi-input ops: cast all inputs to the widest dtype present
# (reference WIDEST_TYPE_CASTS).
WIDEST_TYPE_CASTS = [
    "elemwise_add",
    "elemwise_sub",
    "elemwise_mul",
    "elemwise_div",
    "broadcast_add",
    "broadcast_sub",
    "broadcast_mul",
    "broadcast_div",
    "broadcast_mod",
    "broadcast_power",
    "broadcast_maximum",
    "broadcast_minimum",
    "broadcast_hypot",
    "Concat",
    "concat",
    "stack",
    "where",
    "maximum",
    "minimum",
    "add_n",
]
