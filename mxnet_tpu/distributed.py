"""Multi-process initialization (reference: the ps-lite bootstrap in
``src/kvstore/kvstore_dist.h`` + ``tools/launch.py`` env protocol).

One call wires a worker into the ``jax.distributed`` world using the
environment set by ``tools/launch.py``; after it, ``jax.devices()``
spans every host's chips and the dist kvstore / sharded train steps
reduce over ICI/DCN collectives.
"""
from __future__ import annotations

import os

_initialized = False


def distributed_init(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Initialize the multi-process JAX runtime from arguments or the
    launcher's environment (MXNET_TPU_COORDINATOR / _NUM_PROCS /
    _PROC_ID).  No-op when single-process or already initialized."""
    global _initialized
    if _initialized:
        return False
    coordinator_address = coordinator_address or \
        os.environ.get("MXNET_TPU_COORDINATOR")
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get("MXNET_TPU_NUM_PROCS", "1"))
    process_id = process_id if process_id is not None else \
        int(os.environ.get("MXNET_TPU_PROC_ID", "0"))
    if coordinator_address is None or num_processes <= 1:
        return False
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    return True
