"""Multi-process initialization (reference: the ps-lite bootstrap in
``src/kvstore/kvstore_dist.h`` + ``tools/launch.py`` env protocol).

One call wires a worker into the ``jax.distributed`` world using the
environment set by ``tools/launch.py``; after it, ``jax.devices()``
spans every host's chips and the dist kvstore / sharded train steps
reduce over ICI/DCN collectives.
"""
from __future__ import annotations

import os

_initialized = False


def distributed_init(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Initialize the multi-process JAX runtime from arguments or the
    launcher's environment (MXNET_TPU_COORDINATOR / _NUM_PROCS /
    _PROC_ID).  No-op when single-process or already initialized."""
    global _initialized
    if _initialized:
        return False
    coordinator_address = coordinator_address or \
        os.environ.get("MXNET_TPU_COORDINATOR")
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get("MXNET_TPU_NUM_PROCS", "1"))
    process_id = process_id if process_id is not None else \
        int(os.environ.get("MXNET_TPU_PROC_ID", "0"))
    if coordinator_address is None or num_processes <= 1:
        return False
    import jax
    # CPU backends need a cross-process collectives implementation to
    # join a multi-process world (TPU uses ICI natively)
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    return True


# ----------------------------------------------------------------------
# Host-side collectives.
#
# On TPU pods the backend is multi-process and XLA collectives ride
# ICI/DCN (use those inside jit).  On CPU, ``distributed_init`` wires
# gloo collectives BEFORE backend creation, so the backend world is
# multi-process there too and ``host_allreduce``/``host_broadcast``
# take the same ``process_allgather`` path a pod takes -- exercised
# in-suite by tests/test_distributed.py::
# test_two_process_backend_collectives_gloo.  Only when the backend
# failed to come up multi-process (a jaxlib without gloo, or a backend
# initialized before distributed_init) does the coordination service's
# key-value store carry the reduction -- structurally the reference's
# ps-lite server path: workers push values, every worker pulls and
# reduces.  That fallback funnels O(N*P) bytes through the coordinator
# and warns once (_warn_kv_fallback); it is a test-environment escape
# hatch, never the pod path.
# ----------------------------------------------------------------------

_seq = [0]
_my_old_keys = []   # this rank's keys from past rounds, deleted lazily


def _kv_set(client, key, data):
    if hasattr(client, "key_value_set_bytes"):
        client.key_value_set_bytes(key, data)
    else:
        import base64
        client.key_value_set(key, base64.b64encode(data).decode())


def _kv_get(client, key, timeout_ms):
    if hasattr(client, "blocking_key_value_get_bytes"):
        return client.blocking_key_value_get_bytes(key, timeout_ms)
    import base64
    return base64.b64decode(client.blocking_key_value_get(key,
                                                          timeout_ms))


def _gc_old_keys(client):
    """Delete this rank's keys from two rounds back.  Collectives are
    lockstep on _seq: entering round N+1 implies every rank has POSTED
    round N, hence fully consumed round N-1 -- deleting N-1 entries is
    race-free, and the coordinator store stays bounded."""
    while len(_my_old_keys) > 1:
        key = _my_old_keys.pop(0)
        try:
            client.key_value_delete(key)
        except Exception:
            pass


def world():
    """(num_processes, process_id) of the connected world (1, 0 when
    single-process)."""
    from jax._src import distributed
    gs = distributed.global_state
    if gs.client is None:
        return 1, 0
    return gs.num_processes, gs.process_id


def _client():
    from jax._src import distributed
    return distributed.global_state.client


_KV_FALLBACK_WARNED = [False]


def _warn_kv_fallback():
    """The coordination-service KV transport funnels every rank's full
    tensor through the coordinator: O(N*P) bytes through one process.
    It exists for test environments whose backend world is
    single-process (jax.process_count() == 1 despite a multi-rank
    launcher).  A MULTI-process backend reaching this path means the
    world sizes disagree -- a misconfigured pod where backend
    collectives should have run -- so that case errors instead of
    silently funneling a pod's gradients through one host."""
    import warnings
    import jax
    if jax.process_count() > 1:
        from .base import MXNetError
        raise MXNetError(
            "host collective fallback (coordination-service KV) reached "
            "with a multi-process backend (jax.process_count()=%d != "
            "launcher world): the distributed init is misconfigured; "
            "backend collectives must run on a pod (check "
            "tools/launch.py / JAX distributed init)"
            % jax.process_count())
    if not _KV_FALLBACK_WARNED[0]:
        _KV_FALLBACK_WARNED[0] = True
        warnings.warn(
            "using the coordination-service KV fallback for host "
            "collectives (backend is not multi-process); fine for "
            "tests, never the real-pod path")


def _result_device(arr):
    """Placement the collective's result should land on: the INPUT's
    sharding when it is a jax array (a Sharding is a valid device_put
    target, so mesh-sharded/replicated inputs come back with their
    layout instead of collapsing onto one device).  ``jnp.asarray``
    would place the result on the DEFAULT device instead -- on this
    environment that is a remote tunneled TPU even under
    JAX_PLATFORMS=cpu, so an unplaced result drags every later use
    through the tunnel."""
    import jax
    if isinstance(arr, jax.Array):
        return arr.sharding
    return None


def _place(x, placement):
    import jax
    import jax.numpy as jnp
    import numpy as np
    if placement is None:
        return jnp.asarray(x)
    if isinstance(placement, jax.sharding.Sharding) \
            and not placement.is_fully_addressable:
        # a multi-host sharding (the global mesh of docs/distributed.md)
        # cannot be device_put from host data; build the global array
        # from this process's addressable shards instead -- valid here
        # because collective results are identical on every rank
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, placement,
                                            lambda idx: x[idx])
    return jax.device_put(x, placement)


def _telemetry_collective(kind, nbytes, ntensors):
    from . import telemetry as _telemetry
    if _telemetry._ENABLED:
        _telemetry.hooks.dist_collective(kind, nbytes, ntensors)


def host_allreduce(arr, average=False, timeout_ms=60000, _ntensors=1):
    """Sum (or mean) a host array across every process.  Uses backend
    collectives when the backend is multi-process; otherwise the
    coordination-service KV store.  The result lands on the input's
    device (see ``_result_device``).

    NOT a training-hot-path primitive: the compiled SPMD train step
    reduces gradients in-graph (GSPMD ``all-reduce`` over the global
    mesh, docs/distributed.md); this host collective survives for
    init-time broadcast and metric/overflow reduction only, and those
    call sites coalesce tensors through the ``*_bucketed`` wrappers."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = _result_device(arr)
    nproc, rank = world()
    if nproc == 1:
        return _place(arr, dev)
    _telemetry_collective("allreduce", _nbytes_of(arr), _ntensors)
    if jax.process_count() == nproc:
        from jax.experimental import multihost_utils
        g = multihost_utils.process_allgather(jnp.asarray(arr))
        out = jnp.mean(g, axis=0) if average else jnp.sum(g, axis=0)
        return _place(out, dev)
    _warn_kv_fallback()
    client = _client()
    x = np.asarray(arr)
    _seq[0] += 1
    tag = "mxkv_ar/%d" % _seq[0]
    my_key = "%s/%d" % (tag, rank)
    _kv_set(client, my_key, x.tobytes())
    total = np.zeros_like(x)
    for r in range(nproc):
        raw = _kv_get(client, "%s/%d" % (tag, r), timeout_ms)
        total += np.frombuffer(raw, dtype=x.dtype).reshape(x.shape)
    _my_old_keys.append(my_key)
    _gc_old_keys(client)
    if average:
        total = total / nproc
    return _place(total, dev)


def host_broadcast(arr, root=0, timeout_ms=60000, _ntensors=1):
    """Every process receives root's value (placed on the input's
    device, see ``_result_device``).  Init-time parameter sync only on
    the SPMD path -- see ``host_allreduce``'s note."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = _result_device(arr)
    nproc, rank = world()
    if nproc == 1:
        return _place(arr, dev)
    _telemetry_collective("broadcast", _nbytes_of(arr), _ntensors)
    if jax.process_count() == nproc:
        from jax.experimental import multihost_utils
        out = multihost_utils.broadcast_one_to_all(
            jnp.asarray(arr), is_source=(rank == root))
        return _place(out, dev)
    _warn_kv_fallback()
    client = _client()
    x = np.asarray(arr)
    _seq[0] += 1
    tag = "mxkv_bc/%d" % _seq[0]
    if rank == root:
        _kv_set(client, tag, x.tobytes())
        out = x
    else:
        raw = _kv_get(client, tag, timeout_ms)
        out = np.frombuffer(raw, dtype=x.dtype).reshape(x.shape)
    # broadcast has no natural lockstep (root does not read), so a
    # barrier gates the delete: after it, every rank has consumed the key
    client.wait_at_barrier(tag + "/done", timeout_ms)
    if rank == root:
        try:
            client.key_value_delete(tag)
        except Exception:
            pass
    return _place(out, dev)


def barrier(name="mxnet_tpu_barrier", timeout_ms=60000):
    nproc, _ = world()
    if nproc == 1:
        return
    _seq[0] += 1
    _client().wait_at_barrier("%s/%d" % (name, _seq[0]), timeout_ms)


def _nbytes_of(arr):
    try:
        import numpy as np
        shape = getattr(arr, "shape", ())
        dtype = getattr(arr, "dtype", None)
        if dtype is None:
            return 0
        n = 1
        for d in shape:
            n *= int(d)
        return n * np.dtype(dtype).itemsize
    except Exception:
        return 0


# ----------------------------------------------------------------------
# Bucketed host collectives.
#
# The surviving host-collective call sites (init-time parameter
# broadcast, metric/overflow reduction, the legacy eager kvstore path)
# used to issue ONE RPC PER TENSOR -- for an N-layer model that is N
# coordinator round-trips before the first step.  These wrappers
# flatten a whole list of tensors into one contiguous buffer per dtype
# and make ONE collective per buffer, then split results back onto each
# input's original placement.  ``dist.collectives`` vs
# ``dist.tensors_coalesced`` telemetry records the drop.
# ----------------------------------------------------------------------

def _as_host(x):
    """Host numpy view of one collective operand (NDArray / jax.Array /
    numpy).  Multi-host global arrays must be fully replicated -- which
    every replicated-parameter caller satisfies."""
    import numpy as np
    data = getattr(x, "_data", x)       # NDArray -> jax array
    return np.asarray(data)


def _bucketed(arrays, one_collective):
    """Flatten/concat/split machinery: group ``arrays`` by dtype, run
    ``one_collective(buffer, ntensors)`` once per group, and return the
    per-input results placed back on each input's sharding.  The
    grouping itself is the shared ``mxnet_tpu.bucketing`` helper -- the
    same logic the fused bucket-flattened optimizer update compiles
    over traced buffers (docs/kernels.md)."""
    import numpy as np
    from .bucketing import dtype_groups, flatten_group, split_group
    arrays = list(arrays)
    if not arrays:
        return []
    placements = [_result_device(getattr(a, "_data", a)) for a in arrays]
    hosts = [_as_host(a) for a in arrays]
    out = [None] * len(arrays)
    for _dtype, idxs in dtype_groups(hosts):
        buf = flatten_group(hosts, idxs, np)
        res = np.asarray(one_collective(buf, len(idxs)))
        pieces = split_group(res, [hosts[i].shape for i in idxs])
        for i, piece in zip(idxs, pieces):
            out[i] = _place(piece, placements[i])
    return out


def host_allreduce_bucketed(arrays, average=False, timeout_ms=60000):
    """Sum (or mean) a LIST of host arrays across every process with
    one flattened collective per dtype group instead of one RPC per
    tensor.  Results come back in input order, each on its input's
    placement."""
    nproc, _rank = world()
    if nproc == 1:
        return [_place(_as_host(a),
                       _result_device(getattr(a, "_data", a)))
                for a in arrays]
    return _bucketed(
        arrays,
        lambda buf, n: host_allreduce(buf, average=average,
                                      timeout_ms=timeout_ms,
                                      _ntensors=n))


def host_broadcast_bucketed(arrays, root=0, timeout_ms=60000):
    """Every process receives root's values for a LIST of arrays, one
    flattened collective per dtype group (the init-time parameter-sync
    path of docs/distributed.md)."""
    nproc, _rank = world()
    if nproc == 1:
        return [_place(_as_host(a),
                       _result_device(getattr(a, "_data", a)))
                for a in arrays]
    return _bucketed(
        arrays,
        lambda buf, n: host_broadcast(buf, root=root,
                                      timeout_ms=timeout_ms,
                                      _ntensors=n))
