"""Multi-process initialization (reference: the ps-lite bootstrap in
``src/kvstore/kvstore_dist.h`` + ``tools/launch.py`` env protocol).

One call wires a worker into the ``jax.distributed`` world using the
environment set by ``tools/launch.py``; after it, ``jax.devices()``
spans every host's chips and the dist kvstore / sharded train steps
reduce over ICI/DCN collectives.

Failure model (ISSUE 15, docs/distributed.md): every cross-process
wait in this module -- collective sends/receives and barriers -- is
*attributed*.  A dead or wedged peer never surfaces as a raw jaxlib
``DEADLINE_EXCEEDED``; it surfaces as :class:`BarrierTimeout` /
:class:`RankFailure` carrying the barrier tag, the sequence number,
the missing rank(s) (cross-checked against each rank's liveness lease
key, beaten from the training loop), and the elapsed wait.  Transient
coordination-KV errors -- and only those -- retry with bounded
backoff.  All coordination keys are namespaced by the supervisor
*generation* id (``MXNET_TPU_GENERATION``), so an elastic restart
starts clean and sweeps the dead generation's keys.
"""
from __future__ import annotations

import os
import time

from . import chaos as _chaos
from .base import MXNetError

_initialized = False


class RankFailure(MXNetError):
    """A cross-process operation gave up on one or more peer ranks.

    Carries ``tag`` (the barrier/collective name), ``seq`` (the
    lockstep sequence number), ``ranks`` (the peers attributed --
    missing, aborted, or unreachable), and ``elapsed_s``.
    """

    def __init__(self, msg, tag=None, seq=None, ranks=(), elapsed_s=None):
        super().__init__(msg)
        self.tag = tag
        self.seq = seq
        self.ranks = tuple(ranks)
        self.elapsed_s = elapsed_s


class BarrierTimeout(RankFailure):
    """A barrier rendezvous timed out; ``ranks`` names every rank that
    never acked (``presumed_dead`` the subset whose liveness lease is
    stale or absent)."""

    def __init__(self, msg, tag=None, seq=None, ranks=(), elapsed_s=None,
                 presumed_dead=()):
        super().__init__(msg, tag=tag, seq=seq, ranks=ranks,
                         elapsed_s=elapsed_s)
        self.presumed_dead = tuple(presumed_dead)


class _KVTimeout(Exception):
    """Internal: a blocking KV get hit its deadline.  Callers convert
    it into the typed error that names what they were waiting for."""

    def __init__(self, elapsed_s):
        super().__init__("%.3fs" % elapsed_s)
        self.elapsed_s = elapsed_s


def distributed_init(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Initialize the multi-process JAX runtime from arguments or the
    launcher's environment (MXNET_TPU_COORDINATOR / _NUM_PROCS /
    _PROC_ID).  No-op when single-process or already initialized."""
    global _initialized
    if _initialized:
        return False
    coordinator_address = coordinator_address or \
        os.environ.get("MXNET_TPU_COORDINATOR")
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get("MXNET_TPU_NUM_PROCS", "1"))
    process_id = process_id if process_id is not None else \
        int(os.environ.get("MXNET_TPU_PROC_ID", "0"))
    if coordinator_address is None or num_processes <= 1:
        return False
    import jax
    # CPU backends need a cross-process collectives implementation to
    # join a multi-process world (TPU uses ICI natively)
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    return True


# ----------------------------------------------------------------------
# Host-side collectives.
#
# On TPU pods the backend is multi-process and XLA collectives ride
# ICI/DCN (use those inside jit).  On CPU, ``distributed_init`` wires
# gloo collectives BEFORE backend creation, so the backend world is
# multi-process there too and ``host_allreduce``/``host_broadcast``
# take the same ``process_allgather`` path a pod takes -- exercised
# in-suite by tests/test_distributed.py::
# test_two_process_backend_collectives_gloo.  Only when the backend
# failed to come up multi-process (a jaxlib without gloo, or a backend
# initialized before distributed_init) does the coordination service's
# key-value store carry the reduction -- structurally the reference's
# ps-lite server path: workers push values, every worker pulls and
# reduces.  That fallback funnels O(N*P) bytes through the coordinator
# and warns once (_warn_kv_fallback); it is a test-environment escape
# hatch, never the pod path.
# ----------------------------------------------------------------------

_seq = [0]
_my_old_keys = []   # this rank's keys from past rounds, deleted lazily


def generation():
    """The supervisor generation this process belongs to
    (``MXNET_TPU_GENERATION``, bumped by the elastic restart
    supervisor on every relaunch).  Namespaces every coordination-KV
    key, so a restarted world never reads the dead world's state."""
    try:
        return int(os.environ.get("MXNET_TPU_GENERATION", "0") or 0)
    except ValueError:
        return 0


def _kv_set(client, key, data):
    if hasattr(client, "key_value_set_bytes"):
        client.key_value_set_bytes(key, data)
    else:
        import base64
        client.key_value_set(key, base64.b64encode(data).decode())


def _kv_get(client, key, timeout_ms):
    if hasattr(client, "blocking_key_value_get_bytes"):
        return client.blocking_key_value_get_bytes(key, timeout_ms)
    import base64
    return base64.b64decode(client.blocking_key_value_get(key,
                                                          timeout_ms))


def _is_deadline(exc):
    return "DEADLINE_EXCEEDED" in str(exc)


def _kv_attempt(fn, what, kind, seq):
    """One coordination-KV op under the ``dist.collective`` fail point
    with bounded retry: transient errors (and chaos-injected RAISEs --
    the fail point sits INSIDE the retry domain, so an injected fault
    is tolerated the way real weather is) retry up to
    ``MXNET_TPU_DIST_KV_RETRIES`` times with doubling backoff, each
    tolerated one counted ``chaos.survived('dist.collective')``.  A
    deadline is NOT transient -- it means a peer never produced the
    value -- and converts immediately to :class:`_KVTimeout` for the
    caller to attribute."""
    from . import env as _env
    retries = int(_env.get("MXNET_TPU_DIST_KV_RETRIES"))
    delay = 0.05
    t0 = time.monotonic()
    for attempt in range(retries + 1):
        try:
            # chaos: the host-collective send/recv path -- a RAISE here
            # models a flaky coordination service and must be absorbed
            # by this bounded retry; a KILL is a rank dying mid-exchange
            _chaos.fail_point("dist.collective", what=what, kind=kind,
                              seq=seq, attempt=attempt + 1)
            return fn()
        except _KVTimeout:
            raise
        except Exception as e:
            if _is_deadline(e):
                raise _KVTimeout(time.monotonic() - t0) from e
            if attempt >= retries:
                raise RankFailure(
                    "coordination KV %s %r failed after %d attempt(s): "
                    "%s" % (what, kind, attempt + 1, e),
                    tag=kind, seq=seq,
                    elapsed_s=time.monotonic() - t0) from e
            _chaos.survived("dist.collective", "kv_retry")
            time.sleep(delay)
            delay *= 2


def _kv_set_checked(client, key, data, kind, seq):
    return _kv_attempt(lambda: _kv_set(client, key, data),
                       "set:" + key, kind, seq)


def _kv_get_checked(client, key, timeout_ms, kind, seq):
    return _kv_attempt(lambda: _kv_get(client, key, timeout_ms),
                       "get:" + key, kind, seq)


_PREV_GEN_SWEPT = [False]


def _sweep_previous_generation(client, rank):
    """Once per process (rank 0 only): delete the PREVIOUS supervisor
    generation's coordination keys.  A long-lived coordination service
    (a TPU pod's) carries the dead world's barrier acks, collective
    payloads, and liveness leases across an elastic restart; the new
    generation's first rendezvous sweeps them so stale acks can never
    satisfy a new barrier.  The trailing ``/`` makes each delete a
    recursive directory delete in the coordination service."""
    if _PREV_GEN_SWEPT[0] or rank != 0:
        return
    _PREV_GEN_SWEPT[0] = True
    gen = generation()
    if gen <= 0:
        return
    for prefix in ("mxbar", "mxlive", "mxkv_ar", "mxkv_bc"):
        try:
            client.key_value_delete("%s/g%d/" % (prefix, gen - 1))
        except Exception:
            pass


def _gc_old_keys(client):
    """Delete this rank's keys from two rounds back.  Collectives are
    lockstep on _seq: entering round N+1 implies every rank has POSTED
    round N, hence fully consumed round N-1 -- deleting N-1 entries is
    race-free, and the coordinator store stays bounded.  Also sweeps a
    previous supervisor generation's keys once (see
    :func:`_sweep_previous_generation`)."""
    _sweep_previous_generation(client, world()[1])
    while len(_my_old_keys) > 1:
        key = _my_old_keys.pop(0)
        try:
            client.key_value_delete(key)
        except Exception:
            pass


def world():
    """(num_processes, process_id) of the connected world (1, 0 when
    single-process)."""
    from jax._src import distributed
    gs = distributed.global_state
    if gs.client is None:
        return 1, 0
    return gs.num_processes, gs.process_id


def _client():
    from jax._src import distributed
    return distributed.global_state.client


# ----------------------------------------------------------------------
# Liveness leases.
#
# Attribution needs a second signal besides "no barrier ack": a rank
# that is merely slow still BEATS its lease (the training loop beats it
# every step, and every barrier entry refreshes it), while a dead rank
# stops.  A missing rank whose lease is stale past
# MXNET_TPU_DIST_LEASE_TTL_S (or absent) is *presumed dead* in the
# typed error -- the operator-facing difference between "preempted
# host" and "straggler".  Lease keys live in the coordination KV store
# under the current generation (``mxlive/g<gen>/<rank>``).
# ----------------------------------------------------------------------

def _lease_key(rank):
    return "mxlive/g%d/%d" % (generation(), rank)


def beat_lease():
    """Refresh this rank's liveness lease (no-op single-process).
    Called from the training loop (``ContinuousTrainer``) and at every
    barrier entry; the value is this host's wall clock, compared only
    for staleness (single-digit-seconds skew is harmless against the
    default 10 s TTL)."""
    nproc, rank = world()
    if nproc == 1:
        return False
    try:
        _kv_set(_client(), _lease_key(rank), repr(time.time()).encode())
    except Exception:
        return False            # a failed beat must never kill a step
    return True


def lease_beater():
    """A bound zero-arg beater when this process is part of a
    multi-process world, else ``None`` -- so hot loops pay one
    attribute check per step, never a ``world()`` probe (the
    zero-overhead contract tests/test_resilience.py proves)."""
    return beat_lease if world()[0] > 1 else None


def lease_age(rank, timeout_ms=200):
    """Seconds since ``rank`` last beat its lease, or ``None`` when it
    never has (or the probe timed out)."""
    try:
        raw = _kv_get(_client(), _lease_key(rank), timeout_ms)
        return max(0.0, time.time() - float(raw.decode()))
    except Exception:
        return None


def stale_ranks(ttl_s=None, ranks=None):
    """Ranks whose lease is absent or older than ``ttl_s``
    (``MXNET_TPU_DIST_LEASE_TTL_S``) -- the presumed-dead set."""
    from . import env as _env
    if ttl_s is None:
        ttl_s = float(_env.get("MXNET_TPU_DIST_LEASE_TTL_S"))
    nproc, _rank = world()
    out = []
    for r in range(nproc) if ranks is None else ranks:
        age = lease_age(r)
        if age is None or age > ttl_s:
            out.append(r)
    return out


def _telemetry_rank_failure(kind, tag, ranks, elapsed_s):
    from . import telemetry as _telemetry
    if _telemetry._ENABLED:
        _telemetry.hooks.dist_rank_failure(kind, tag, ranks, elapsed_s)


_KV_FALLBACK_WARNED = [False]


def _warn_kv_fallback():
    """The coordination-service KV transport funnels every rank's full
    tensor through the coordinator: O(N*P) bytes through one process.
    It exists for test environments whose backend world is
    single-process (jax.process_count() == 1 despite a multi-rank
    launcher).  A MULTI-process backend reaching this path means the
    world sizes disagree -- a misconfigured pod where backend
    collectives should have run -- so that case errors instead of
    silently funneling a pod's gradients through one host."""
    import warnings
    import jax
    if jax.process_count() > 1:
        from .base import MXNetError
        raise MXNetError(
            "host collective fallback (coordination-service KV) reached "
            "with a multi-process backend (jax.process_count()=%d != "
            "launcher world): the distributed init is misconfigured; "
            "backend collectives must run on a pod (check "
            "tools/launch.py / JAX distributed init)"
            % jax.process_count())
    if not _KV_FALLBACK_WARNED[0]:
        _KV_FALLBACK_WARNED[0] = True
        warnings.warn(
            "using the coordination-service KV fallback for host "
            "collectives (backend is not multi-process); fine for "
            "tests, never the real-pod path")


def _result_device(arr):
    """Placement the collective's result should land on: the INPUT's
    sharding when it is a jax array (a Sharding is a valid device_put
    target, so mesh-sharded/replicated inputs come back with their
    layout instead of collapsing onto one device).  ``jnp.asarray``
    would place the result on the DEFAULT device instead -- on this
    environment that is a remote tunneled TPU even under
    JAX_PLATFORMS=cpu, so an unplaced result drags every later use
    through the tunnel."""
    import jax
    if isinstance(arr, jax.Array):
        return arr.sharding
    return None


def _place(x, placement):
    import jax
    import jax.numpy as jnp
    import numpy as np
    if placement is None:
        return jnp.asarray(x)
    if isinstance(placement, jax.sharding.Sharding) \
            and not placement.is_fully_addressable:
        # a multi-host sharding (the global mesh of docs/distributed.md)
        # cannot be device_put from host data; build the global array
        # from this process's addressable shards instead -- valid here
        # because collective results are identical on every rank
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, placement,
                                            lambda idx: x[idx])
    return jax.device_put(x, placement)


def _telemetry_collective(kind, nbytes, ntensors):
    from . import telemetry as _telemetry
    if _telemetry._ENABLED:
        _telemetry.hooks.dist_collective(kind, nbytes, ntensors)


def host_allreduce(arr, average=False, timeout_ms=60000, _ntensors=1):
    """Sum (or mean) a host array across every process.  Uses backend
    collectives when the backend is multi-process; otherwise the
    coordination-service KV store.  The result lands on the input's
    device (see ``_result_device``).

    NOT a training-hot-path primitive: the compiled SPMD train step
    reduces gradients in-graph (GSPMD ``all-reduce`` over the global
    mesh, docs/distributed.md); this host collective survives for
    init-time broadcast and metric/overflow reduction only, and those
    call sites coalesce tensors through the ``*_bucketed`` wrappers."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = _result_device(arr)
    nproc, rank = world()
    if nproc == 1:
        return _place(arr, dev)
    _telemetry_collective("allreduce", _nbytes_of(arr), _ntensors)
    if jax.process_count() == nproc:
        # chaos: the pod-shaped transport (gloo/ICI backend collective)
        _chaos.fail_point("dist.collective", what="allgather",
                          kind="allreduce", seq=_seq[0])
        from jax.experimental import multihost_utils
        try:
            g = multihost_utils.process_allgather(jnp.asarray(arr))
        except RankFailure:
            raise
        except Exception as e:
            elapsed = None
            dead = stale_ranks()
            _telemetry_rank_failure("collective", "allreduce", dead,
                                    elapsed)
            raise RankFailure(
                "backend allgather failed: %s%s"
                % (e, "; presumed dead rank(s): %s" % dead if dead
                   else ""),
                tag="allreduce", ranks=dead) from e
        out = jnp.mean(g, axis=0) if average else jnp.sum(g, axis=0)
        return _place(out, dev)
    _warn_kv_fallback()
    client = _client()
    x = np.asarray(arr)
    _seq[0] += 1
    seq = _seq[0]
    tag = "mxkv_ar/g%d/%d" % (generation(), seq)
    my_key = "%s/%d" % (tag, rank)
    _kv_set_checked(client, my_key, x.tobytes(), "allreduce", seq)
    total = np.zeros_like(x)
    t0 = time.monotonic()
    for r in range(nproc):
        try:
            raw = _kv_get_checked(client, "%s/%d" % (tag, r),
                                  timeout_ms, "allreduce", seq)
        except _KVTimeout as e:
            dead = stale_ranks(ranks=[r])
            _telemetry_rank_failure("collective", "allreduce", [r],
                                    e.elapsed_s)
            raise RankFailure(
                "allreduce (seq %d) timed out after %.1fs waiting for "
                "rank %d's value%s" % (
                    seq, time.monotonic() - t0, r,
                    " (presumed dead: lease stale/absent)" if dead
                    else ""),
                tag="allreduce", seq=seq, ranks=[r],
                elapsed_s=time.monotonic() - t0) from e
        total += np.frombuffer(raw, dtype=x.dtype).reshape(x.shape)
    _my_old_keys.append(my_key)
    _gc_old_keys(client)
    if average:
        total = total / nproc
    return _place(total, dev)


def host_broadcast(arr, root=0, timeout_ms=60000, _ntensors=1):
    """Every process receives root's value (placed on the input's
    device, see ``_result_device``).  Init-time parameter sync only on
    the SPMD path -- see ``host_allreduce``'s note."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = _result_device(arr)
    nproc, rank = world()
    if nproc == 1:
        return _place(arr, dev)
    _telemetry_collective("broadcast", _nbytes_of(arr), _ntensors)
    if jax.process_count() == nproc:
        # chaos: the pod-shaped transport (gloo/ICI backend collective)
        _chaos.fail_point("dist.collective", what="broadcast",
                          kind="broadcast", seq=_seq[0])
        from jax.experimental import multihost_utils
        out = multihost_utils.broadcast_one_to_all(
            jnp.asarray(arr), is_source=(rank == root))
        return _place(out, dev)
    _warn_kv_fallback()
    client = _client()
    x = np.asarray(arr)
    _seq[0] += 1
    seq = _seq[0]
    tag = "mxkv_bc/g%d/%d" % (generation(), seq)
    if rank == root:
        _kv_set_checked(client, tag, x.tobytes(), "broadcast", seq)
        out = x
    else:
        try:
            raw = _kv_get_checked(client, tag, timeout_ms,
                                  "broadcast", seq)
        except _KVTimeout as e:
            dead = stale_ranks(ranks=[root])
            _telemetry_rank_failure("collective", "broadcast", [root],
                                    e.elapsed_s)
            raise RankFailure(
                "broadcast (seq %d) timed out after %.1fs waiting for "
                "root rank %d%s" % (
                    seq, e.elapsed_s, root,
                    " (presumed dead: lease stale/absent)" if dead
                    else ""),
                tag="broadcast", seq=seq, ranks=[root],
                elapsed_s=e.elapsed_s) from e
        out = np.frombuffer(raw, dtype=x.dtype).reshape(x.shape)
    # broadcast has no natural lockstep (root does not read), so an
    # attributed rendezvous gates the delete: after it, every rank has
    # consumed the key
    _wait_ranks("mxkv_bc_done", seq, nproc, rank, timeout_ms)
    if rank == root:
        try:
            client.key_value_delete(tag)
        except Exception:
            pass
    return _place(out, dev)


def failfast_exit(code=3):
    """Exit NOW, skipping the jax distributed client's shutdown
    barrier.  A survivor holding a typed :class:`RankFailure` cannot
    shut down cleanly: the coordination client's destructor waits at a
    shutdown barrier the dead rank will never join and LOG(FATAL)s the
    interpreter (SIGABRT) mid-teardown, burying the attributed error
    under coordination-service noise.  This flushes stdio and the
    telemetry sinks, then ``os._exit(code)`` -- the supervised-worker
    exit the elastic restart supervisor relaunches on (any nonzero
    exit triggers the relaunch; this one keeps the log and the exit
    code honest)."""
    import sys
    try:
        from . import telemetry as _telemetry
        if _telemetry._ENABLED:
            _telemetry.flush()
    except Exception:
        pass
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    os._exit(code)


def barrier(name="mxnet_tpu_barrier", timeout_ms=None):
    """Attributed rendezvous: every rank posts an ack key and waits for
    every other rank's, so a timeout NAMES the missing rank(s) in a
    typed :class:`BarrierTimeout` (never a raw jaxlib
    ``DEADLINE_EXCEEDED`` -- the pre-ISSUE-15 behavior was a 60 s hang
    followed by an unattributed KV exception on every survivor).
    ``timeout_ms`` defaults to ``MXNET_TPU_DIST_BARRIER_TIMEOUT_MS``.
    A rank that posted an *abort* ack (:func:`post_abort`) raises
    :class:`RankFailure` on every waiter instead -- the fast path a
    failing-but-alive peer takes so survivors never wait out the
    bound."""
    nproc, rank = world()
    if nproc == 1:
        return
    _seq[0] += 1
    _wait_ranks(name, _seq[0], nproc, rank, timeout_ms)


def post_abort(name, reason=""):
    """Mark the NEXT rendezvous at ``name`` aborted, so peers waiting
    there fail fast with a typed :class:`RankFailure` instead of
    waiting out the barrier bound.  Called by a rank that cannot
    complete a multi-rank protocol (e.g. a failed shard write inside
    ``save_sharded``); consumes the same lockstep seq the skipped
    barrier would have, so an aborting world stays seq-aligned."""
    nproc, rank = world()
    if nproc == 1:
        return
    _seq[0] += 1
    key = "mxbar/g%d/%s/%d/%d" % (generation(), name, _seq[0], rank)
    try:
        _kv_set(_client(), key,
                b"abort:" + reason.encode("utf-8", "replace"))
    except Exception:
        pass                    # peers then attribute via the timeout


def _wait_ranks(name, seq, nproc, rank, timeout_ms):
    """The rendezvous body shared by :func:`barrier` and the broadcast
    consumption gate: post ``mxbar/g<gen>/<name>/<seq>/<rank>``, then
    collect every peer's ack within the deadline."""
    from . import env as _env
    if timeout_ms is None:
        timeout_ms = int(_env.get("MXNET_TPU_DIST_BARRIER_TIMEOUT_MS"))
    client = _client()
    beat_lease()                # rendezvousing is proof of life
    base = "mxbar/g%d/%s/%d" % (generation(), name, seq)
    my_key = "%s/%d" % (base, rank)
    t0 = time.monotonic()
    _kv_set_checked(client, my_key, b"ok", name, seq)
    deadline = t0 + timeout_ms / 1000.0
    missing, aborted = [], []
    for r in range(nproc):
        if r == rank:
            continue
        remaining_ms = max(1, int(1000 * (deadline - time.monotonic())))
        try:
            val = _kv_get_checked(client, "%s/%d" % (base, r),
                                  remaining_ms, name, seq)
        except _KVTimeout:
            missing.append(r)
            # the deadline is spent; probe the remaining ranks with a
            # short grace each so the error names EVERY missing rank
            deadline = time.monotonic() + 0.2
            continue
        if val.startswith(b"abort"):
            aborted.append(r)
    _my_old_keys.append(my_key)
    _gc_old_keys(client)
    elapsed = time.monotonic() - t0
    if missing:
        dead = stale_ranks(ranks=missing)
        _telemetry_rank_failure("barrier", name, missing, elapsed)
        raise BarrierTimeout(
            "barrier %r (seq %d) timed out after %.1fs waiting for "
            "rank(s) %s%s" % (
                name, seq, elapsed, missing,
                "; presumed dead (liveness lease stale/absent): %s"
                % dead if dead else "; leases fresh (slow peer?)"),
            tag=name, seq=seq, ranks=missing, elapsed_s=elapsed,
            presumed_dead=dead)
    if aborted:
        _telemetry_rank_failure("abort", name, aborted, elapsed)
        raise RankFailure(
            "rank(s) %s aborted at barrier %r (seq %d) after %.1fs"
            % (aborted, name, seq, elapsed),
            tag=name, seq=seq, ranks=aborted, elapsed_s=elapsed)


def _nbytes_of(arr):
    try:
        import numpy as np
        shape = getattr(arr, "shape", ())
        dtype = getattr(arr, "dtype", None)
        if dtype is None:
            return 0
        n = 1
        for d in shape:
            n *= int(d)
        return n * np.dtype(dtype).itemsize
    except Exception:
        return 0


# ----------------------------------------------------------------------
# Bucketed host collectives.
#
# The surviving host-collective call sites (init-time parameter
# broadcast, metric/overflow reduction, the legacy eager kvstore path)
# used to issue ONE RPC PER TENSOR -- for an N-layer model that is N
# coordinator round-trips before the first step.  These wrappers
# flatten a whole list of tensors into one contiguous buffer per dtype
# and make ONE collective per buffer, then split results back onto each
# input's original placement.  ``dist.collectives`` vs
# ``dist.tensors_coalesced`` telemetry records the drop.
# ----------------------------------------------------------------------

def _as_host(x):
    """Host numpy view of one collective operand (NDArray / jax.Array /
    numpy).  Multi-host global arrays must be fully replicated -- which
    every replicated-parameter caller satisfies."""
    import numpy as np
    data = getattr(x, "_data", x)       # NDArray -> jax array
    return np.asarray(data)


def _bucketed(arrays, one_collective):
    """Flatten/concat/split machinery: group ``arrays`` by dtype, run
    ``one_collective(buffer, ntensors)`` once per group, and return the
    per-input results placed back on each input's sharding.  The
    grouping itself is the shared ``mxnet_tpu.bucketing`` helper -- the
    same logic the fused bucket-flattened optimizer update compiles
    over traced buffers (docs/kernels.md)."""
    import numpy as np
    from .bucketing import dtype_groups, flatten_group, split_group
    arrays = list(arrays)
    if not arrays:
        return []
    placements = [_result_device(getattr(a, "_data", a)) for a in arrays]
    hosts = [_as_host(a) for a in arrays]
    out = [None] * len(arrays)
    for _dtype, idxs in dtype_groups(hosts):
        buf = flatten_group(hosts, idxs, np)
        res = np.asarray(one_collective(buf, len(idxs)))
        pieces = split_group(res, [hosts[i].shape for i in idxs])
        for i, piece in zip(idxs, pieces):
            out[i] = _place(piece, placements[i])
    return out


def host_allreduce_bucketed(arrays, average=False, timeout_ms=60000):
    """Sum (or mean) a LIST of host arrays across every process with
    one flattened collective per dtype group instead of one RPC per
    tensor.  Results come back in input order, each on its input's
    placement."""
    nproc, _rank = world()
    if nproc == 1:
        return [_place(_as_host(a),
                       _result_device(getattr(a, "_data", a)))
                for a in arrays]
    return _bucketed(
        arrays,
        lambda buf, n: host_allreduce(buf, average=average,
                                      timeout_ms=timeout_ms,
                                      _ntensors=n))


def host_broadcast_bucketed(arrays, root=0, timeout_ms=60000):
    """Every process receives root's values for a LIST of arrays, one
    flattened collective per dtype group (the init-time parameter-sync
    path of docs/distributed.md)."""
    nproc, _rank = world()
    if nproc == 1:
        return [_place(_as_host(a),
                       _result_device(getattr(a, "_data", a)))
                for a in arrays]
    return _bucketed(
        arrays,
        lambda buf, n: host_broadcast(buf, root=root,
                                      timeout_ms=timeout_ms,
                                      _ntensors=n))
