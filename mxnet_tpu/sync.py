"""Instrumented synchronization layer (ISSUE 5 runtime half).

PRs 2-4 gave six subsystems their own ``threading.Lock``/``Condition``/
``Event`` discipline; this module is the one place that discipline is
*enforced*.  Framework code creates primitives through the factories
here instead of ``threading`` directly:

    self._lock = sync.Lock(name="telemetry.registry")

- **Flag off** (the default): each factory returns the raw ``threading``
  primitive -- zero wrappers, zero overhead, proven by
  ``tests/test_sync.py::test_off_mode_returns_raw_primitives``.
- **Flag on** (``MXNET_TPU_TSAN=1`` or :func:`enable`): factories return
  sanitizing wrappers that

  * record per-thread acquisition stacks and a global *lock-order
    graph* of observed nestings (the runtime closure of the static
    ``lock-order-inversion`` pass in ``analysis/concurrency.py``,
    exactly as ``compile.retraces`` closed the static retrace auditor);
  * raise :class:`LockOrderError` the moment an acquisition would
    create an A/B--B/A cycle -- *before* the schedule that actually
    deadlocks ever runs;
  * time-bound every untimed blocking acquisition/wait with a
    **deadlock watchdog** (``MXNET_TPU_TSAN_WATCHDOG_S``, default 20s)
    that dumps every thread's stack plus the table of who holds which
    lock (acquired where) and raises :class:`DeadlockError`;
  * emit ``sync.*`` telemetry (contention waits, hold times, watchdog
    fires, recorded inversions) when telemetry is also enabled.

Lock *names* are role identities: every ``Instrument._lock`` shares the
name ``telemetry.instrument``, so the order graph reasons about roles
(the same granularity the static pass sees), not instances.  Unnamed
locks get a ``file:line`` creation-site identity.  The nesting
discipline itself is documented in docs/concurrency.md.
"""
from __future__ import annotations

import os
import sys
import threading as _threading
import time
import traceback

__all__ = [
    "Lock", "RLock", "Condition", "Event",
    "enable", "disable", "tsan_enabled", "configure",
    "DeadlockError", "LockOrderError",
    "order_graph", "recorded_reports", "reset_state", "seed_static_order",
    "watchdog_seconds",
]


class DeadlockError(RuntimeError):
    """The watchdog expired on a blocking acquisition/wait: some thread
    has held the needed lock longer than ``MXNET_TPU_TSAN_WATCHDOG_S``.
    The message carries every thread's stack and the held-locks table."""


class LockOrderError(RuntimeError):
    """An acquisition would close a cycle in the observed+static
    lock-order graph -- the A/B--B/A pattern that deadlocks under the
    wrong schedule even if THIS run got lucky."""


# -- module state ------------------------------------------------------
# The flag is read at *factory* time (which wrapper class you get) and
# at wrapper *use* time (so a test's leftover wrappers turn inert after
# disable()).  Everything below uses raw threading primitives: the
# sanitizer must not sanitize itself.

_TSAN = os.environ.get("MXNET_TPU_TSAN", "0") != "0"
_RAISE_ON_INVERSION = True

_tls = _threading.local()            # per-thread held-lock stack
_meta_lock = _threading.Lock()       # guards the structures below
_order = {}                          # name -> set(successor names)
_edge_sites = {}                     # (a, b) -> "thread/stack" of first obs
_held_by_thread = {}                 # thread ident -> shared held list
_reports = []                        # report-only inversion texts
_static_seeded = False
_seeding = False


def _watchdog_default():
    try:
        return float(os.environ.get("MXNET_TPU_TSAN_WATCHDOG_S", "20"))
    except ValueError:
        return 20.0


_WATCHDOG_S = _watchdog_default()

# contention/hold telemetry floor: micro-acquisitions (every uncontended
# acquire "waits" a few ns of syscall time) would otherwise stream a
# timer sample per lock op and drown the run log
_EMIT_THRESHOLD_S = 1e-3


def watchdog_seconds():
    return _WATCHDOG_S


def tsan_enabled():
    return _TSAN


def enable(watchdog_s=None, seed_static=True):
    """Turn the sanitizer on for primitives created from now on.
    ``seed_static=True`` (default) folds the static pass's
    acquisition-order edges into the runtime graph, so the first
    runtime nesting that contradicts the *code's* order -- not just a
    previously observed one -- already raises."""
    global _TSAN, _WATCHDOG_S
    _TSAN = True
    if watchdog_s is not None:
        _WATCHDOG_S = float(watchdog_s)
    if seed_static:
        seed_static_order()


def disable():
    global _TSAN
    _TSAN = False


def configure(raise_on_inversion=None, watchdog_s=None):
    """Tune sanitizer behavior.  ``raise_on_inversion=False`` switches
    to report-only mode (inversions are recorded in
    :func:`recorded_reports` and counted in telemetry, but execution
    proceeds -- letting a *true* deadlock form for the watchdog, or a
    long soak run collect every ordering violation at once)."""
    global _RAISE_ON_INVERSION, _WATCHDOG_S
    if raise_on_inversion is not None:
        _RAISE_ON_INVERSION = bool(raise_on_inversion)
    if watchdog_s is not None:
        _WATCHDOG_S = float(watchdog_s)


def reset_state():
    """Drop the observed order graph, reports, and held-lock table
    (tests; a fresh process needs nothing)."""
    global _static_seeded
    with _meta_lock:
        _order.clear()
        _edge_sites.clear()
        _reports.clear()
        _held_by_thread.clear()
        _static_seeded = False


def order_graph():
    """Copy of the current lock-order graph ``{name: set(successors)}``."""
    with _meta_lock:
        return {a: set(bs) for a, bs in _order.items()}


def recorded_reports():
    """Inversion reports collected in report-only mode."""
    with _meta_lock:
        return list(_reports)


def seed_static_order():
    """Fold ``analysis.concurrency``'s static acquisition-order edges
    (over the installed package) into the runtime graph.  Best-effort:
    the sanitizer works from pure observation when the analysis pass or
    the package source is unavailable."""
    global _static_seeded, _seeding
    if _static_seeded or _seeding:
        return 0
    _seeding = True
    try:
        from .analysis import concurrency as _conc
        pkg_dir = os.path.dirname(os.path.abspath(__file__))
        edges = _conc.static_order_edges([pkg_dir])
    except Exception:
        edges = ()
    finally:
        _seeding = False
    n = 0
    with _meta_lock:
        for a, b in edges:
            if a != b:
                _order.setdefault(a, set()).add(b)
                _edge_sites.setdefault((a, b), "static analysis "
                                      "(analysis/concurrency.py)")
                n += 1
        _static_seeded = True
    return n


# -- held-lock bookkeeping ---------------------------------------------

class _Held:
    __slots__ = ("lock", "name", "t0", "site")

    def __init__(self, lock, name, site):
        self.lock = lock
        self.name = name
        self.t0 = time.perf_counter()
        self.site = site


def _held_stack():
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
        with _meta_lock:
            _held_by_thread[_threading.get_ident()] = stack
    return stack


def _acq_site(limit=12):
    """Cheap acquisition-stack capture: raw (file, line, fn) tuples per
    frame -- no FrameSummary, no linecache -- formatted lazily by
    :func:`_format_site` only when a report is actually built.  This
    runs on EVERY sanitized acquisition, so it must stay microseconds."""
    f = sys._getframe(2)
    out = []
    while f is not None and len(out) < limit:
        code = f.f_code
        out.append((code.co_filename, f.f_lineno, code.co_name))
        f = f.f_back
    return out


def _format_site(site):
    if isinstance(site, str):
        return site
    return "".join('  File "%s", line %d, in %s\n' % t
                   for t in reversed(site))


def _telemetry():
    # late, guarded import: telemetry itself creates locks through this
    # module, so the dependency must stay one-way at import time
    try:
        from . import telemetry
    except ImportError:
        return None
    return telemetry if telemetry._ENABLED else None


def _emit(hook, *args):
    """Guarded telemetry emission: the instruments' own locks are sync
    locks, so an unguarded emit-on-release would recurse forever
    (hold_time's release emitting hold_time...)."""
    if getattr(_tls, "in_hook", False):
        return
    tel = _telemetry()
    if tel is None:
        return
    _tls.in_hook = True
    try:
        getattr(tel.hooks, hook)(*args)
    finally:
        _tls.in_hook = False


def _creation_site():
    f = sys._getframe(2)
    return "%s:%d" % (os.path.basename(f.f_code.co_filename), f.f_lineno)


# -- the order graph ----------------------------------------------------

def _path_exists(src, dst):
    """DFS reachability in _order; caller holds _meta_lock."""
    seen = set()
    stack = [src]
    while stack:
        node = stack.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        stack.extend(_order.get(node, ()))
    return False


def _cycle_path(src, dst):
    """One path src -> ... -> dst in _order; caller holds _meta_lock."""
    seen = {src}
    path = [src]

    def dfs(node):
        if node == dst:
            return True
        for nxt in sorted(_order.get(node, ())):
            if nxt in seen:
                continue
            seen.add(nxt)
            path.append(nxt)
            if dfs(nxt):
                return True
            path.pop()
        return False

    dfs(src)
    return path


def _record_edge(held, name, acq_site):
    """Add edge held.name -> name; detect and handle inversions."""
    report = None
    with _meta_lock:
        a, b = held.name, name
        if b != a:
            if _path_exists(b, a):
                path = _cycle_path(b, a)
                lines = [
                    "mxnet_tpu.sync: LOCK-ORDER INVERSION",
                    "thread %r acquires %r while holding %r," %
                    (_threading.current_thread().name, b, a),
                    "but the order graph already requires %s -> %s:"
                    % (" -> ".join(path), b),
                ]
                for x, y in zip(path, path[1:] + [b]):
                    site = _edge_sites.get((x, y))
                    if site:
                        lines.append("  edge %s -> %s first observed:\n%s"
                                     % (x, y, _format_site(site)))
                lines.append("holding %r acquired at:\n%s"
                             % (a, _format_site(held.site)))
                lines.append("acquiring %r at:\n%s"
                             % (b, _format_site(acq_site)))
                report = "\n".join(lines)
                _reports.append(report)
            _order.setdefault(a, set()).add(b)
            _edge_sites.setdefault((a, b), acq_site)
    if report is not None:
        _emit("sync_inversion", held.name, name)
        if _RAISE_ON_INVERSION:
            raise LockOrderError(report)


def _all_stacks_report(waiter_name, waited_s):
    """The watchdog dump: every thread's stack + the held-locks table."""
    lines = [
        "mxnet_tpu.sync: DEADLOCK watchdog expired after %.1fs waiting "
        "to acquire %r" % (waited_s, waiter_name),
        "",
        "held locks by thread:",
    ]
    with _meta_lock:
        held_snapshot = {ident: [(h.name, h.site) for h in stack]
                         for ident, stack in _held_by_thread.items()
                         if stack}
    names = {t.ident: t.name for t in _threading.enumerate()}
    for ident, held in sorted(held_snapshot.items()):
        lines.append("  thread %r (%s):"
                     % (names.get(ident, "?"), ident))
        for name, site in held:
            lines.append("    holds %r acquired at:\n%s"
                         % (name, _indent(_format_site(site))))
    if not held_snapshot:
        lines.append("  (none recorded)")
    lines.append("")
    lines.append("all thread stacks:")
    frames = sys._current_frames()
    for ident, frame in frames.items():
        lines.append("  thread %r (%s):" % (names.get(ident, "?"), ident))
        lines.append(_indent("".join(traceback.format_stack(frame,
                                                            limit=16))))
    return "\n".join(lines)


def _indent(text, pad="      "):
    return "\n".join(pad + ln for ln in text.splitlines())


def _watchdog_fire(name, waited_s):
    _emit("sync_watchdog", name)
    return DeadlockError(_all_stacks_report(name, waited_s))


# -- wrappers ----------------------------------------------------------

class _TsanLockBase:
    """Shared acquire/release instrumentation for Lock and RLock."""

    _reentrant = False

    def __init__(self, name=None):
        self.name = name or _creation_site()
        self._inner = self._make_inner()

    def acquire(self, blocking=True, timeout=-1):
        if not _TSAN:                # disabled after creation: passthrough
            return self._inner.acquire(blocking, timeout)
        held = _held_stack()
        reentry = self._reentrant and any(h.lock is self for h in held)
        if not blocking:
            got = self._inner.acquire(False)
            if got and not reentry:
                self._on_acquired(held, 0.0)
            return got
        t0 = time.perf_counter()
        if timeout is not None and timeout >= 0:
            got = self._inner.acquire(True, timeout)
            if got and not reentry:
                self._on_acquired(held, time.perf_counter() - t0)
            return got
        got = self._inner.acquire(True, _WATCHDOG_S)
        waited = time.perf_counter() - t0
        if not got:
            raise _watchdog_fire(self.name, waited)
        if not reentry:
            self._on_acquired(held, waited)
        return True

    def _on_acquired(self, held, waited):
        acq_site = _acq_site()
        if held:
            try:
                _record_edge(held[-1], self.name, acq_site)
            except LockOrderError:
                # the caller never observed a successful acquire
                self._inner.release()
                raise
        if waited > _EMIT_THRESHOLD_S:
            _emit("sync_contention", self.name, waited)
        held.append(_Held(self, self.name, acq_site))

    def release(self):
        if _TSAN:
            held = _held_stack()
            for i in range(len(held) - 1, -1, -1):
                if held[i].lock is self:
                    entry = held.pop(i)
                    if not (self._reentrant
                            and any(h.lock is self for h in held)):
                        held_s = time.perf_counter() - entry.t0
                        if held_s > _EMIT_THRESHOLD_S:
                            _emit("sync_hold", self.name, held_s)
                    break
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return "<sync.%s %r>" % (type(self).__name__, self.name)


class _TsanLock(_TsanLockBase):
    _reentrant = False

    @staticmethod
    def _make_inner():
        return _threading.Lock()


class _TsanRLock(_TsanLockBase):
    _reentrant = True

    @staticmethod
    def _make_inner():
        return _threading.RLock()

    def locked(self):                       # RLock has no .locked()
        raise AttributeError("RLock has no locked()")

    def _is_owned(self):                    # Condition integration
        return self._inner._is_owned()


class _TsanCondition:
    """Condition over a sanitized lock: ``with cond:`` goes through the
    wrapper (order graph + watchdog), ``wait()`` temporarily retires
    the lock from the held stack (the condition releases it) and
    watchdog-bounds an untimed wait."""

    def __init__(self, lock=None, name=None):
        if lock is None:
            lock = _TsanLock(name=(name or _creation_site()) + ".lock")
        self._lock = lock
        self.name = name or getattr(lock, "name", None) or _creation_site()
        inner = lock._inner if isinstance(lock, _TsanLockBase) else lock
        self._inner = _threading.Condition(inner)

    def acquire(self, *args, **kwargs):
        return self._lock.acquire(*args, **kwargs)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def _retire_held(self):
        if not (_TSAN and isinstance(self._lock, _TsanLockBase)):
            return None
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self._lock:
                return held.pop(i)
        return None

    def _restore_held(self, entry):
        if entry is not None:
            entry.t0 = time.perf_counter()
            _held_stack().append(entry)

    def wait(self, timeout=None):
        entry = self._retire_held()
        try:
            if timeout is not None or not _TSAN:
                return self._inner.wait(timeout)
            t0 = time.perf_counter()
            got = self._inner.wait(_WATCHDOG_S)
            if not got:
                raise _watchdog_fire(self.name,
                                     time.perf_counter() - t0)
            return got
        finally:
            self._restore_held(entry)

    def wait_for(self, predicate, timeout=None):
        # mirrors threading.Condition.wait_for, through our wait()
        endtime = None
        waittime = timeout
        result = predicate()
        while not result:
            if waittime is not None:
                if endtime is None:
                    endtime = time.monotonic() + waittime
                else:
                    waittime = endtime - time.monotonic()
                    if waittime <= 0:
                        break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n=1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()

    def __repr__(self):
        return "<sync.Condition %r>" % self.name


class _TsanEvent:
    """Event whose *untimed* wait is watchdog-bounded: a flag nobody
    ever sets is the single-threaded spelling of a deadlock."""

    def __init__(self, name=None):
        self.name = name or _creation_site()
        self._inner = _threading.Event()

    def is_set(self):
        return self._inner.is_set()

    def set(self):
        self._inner.set()

    def clear(self):
        self._inner.clear()

    def wait(self, timeout=None):
        if timeout is not None or not _TSAN:
            return self._inner.wait(timeout)
        t0 = time.perf_counter()
        got = self._inner.wait(_WATCHDOG_S)
        if not got:
            raise _watchdog_fire(self.name, time.perf_counter() - t0)
        return got

    def __repr__(self):
        return "<sync.Event %r>" % self.name


# -- factories ---------------------------------------------------------
# Flag off: the raw threading primitive, so the sanitized build and the
# production build differ by ONE branch per primitive *creation* and
# nothing per acquisition.

def Lock(name=None):
    """A mutex; sanitized under ``MXNET_TPU_TSAN=1``, raw otherwise."""
    return _TsanLock(name) if _TSAN else _threading.Lock()


def RLock(name=None):
    """A reentrant mutex; reacquisition by the owner adds no edges."""
    return _TsanRLock(name) if _TSAN else _threading.RLock()


def Condition(lock=None, name=None):
    """A condition variable; pass a :func:`Lock` result to share it."""
    if not _TSAN:
        return (_threading.Condition(lock)
                if not isinstance(lock, _TsanLockBase)
                else _threading.Condition(lock._inner))
    return _TsanCondition(lock, name=name)


def Event(name=None):
    """An event; its untimed ``wait()`` is watchdog-bounded under TSAN."""
    return _TsanEvent(name) if _TSAN else _threading.Event()
