"""Linear-algebra operators (reference: ``src/operator/tensor/la_op.cc``
-- the ``mx.nd.linalg_*`` family).

All lower onto jax.numpy.linalg / lax.linalg, which XLA maps to the
MXU-tiled factorization kernels on TPU.  Batch dimensions are supported
everywhere (leading dims broadcast), matching the reference's batched
BLAS/LAPACK semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _t(x):
    return jnp.swapaxes(x, -1, -2)


@register("linalg_gemm", args=("A", "B", "C"))
def _linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                 beta=1.0, axis=-2):
    """C' = alpha * op(A) op(B) + beta * C (reference: ``linalg_gemm``)."""
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_gemm2", args=("A", "B"))
def _linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                  axis=-2):
    """alpha * op(A) op(B) (reference: ``linalg_gemm2``)."""
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf", args=("A",))
def _linalg_potrf(A):
    """Cholesky factor L with A = L L^T (reference: ``linalg_potrf``)."""
    return jnp.linalg.cholesky(A)


@register("linalg_potri", args=("A",))
def _linalg_potri(A):
    """Inverse from a Cholesky factor: given L, return (L L^T)^-1
    (reference: ``linalg_potri``)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(_t(linv), linv)


@register("linalg_trsm", args=("A", "B"))
def _linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B) with triangular A
    (reference: ``linalg_trsm``)."""
    solve = jax.scipy.linalg.solve_triangular
    if rightside:
        # X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T
        out = solve(_t(A), _t(alpha * B), lower=not lower,
                    trans=1 if transpose else 0)
        return _t(out)
    return solve(A, alpha * B, lower=lower, trans=1 if transpose else 0)


@register("linalg_trmm", args=("A", "B"))
def _linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    """Triangular matmul op(A) B (reference: ``linalg_trmm``)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = _t(tri)
    if rightside:
        return alpha * jnp.matmul(B, tri)
    return alpha * jnp.matmul(tri, B)


@register("linalg_syrk", args=("A",))
def _linalg_syrk(A, transpose=False, alpha=1.0):
    """alpha A A^T (or A^T A) (reference: ``linalg_syrk``)."""
    if transpose:
        return alpha * jnp.matmul(_t(A), A)
    return alpha * jnp.matmul(A, _t(A))


@register("linalg_sumlogdiag", args=("A",))
def _linalg_sumlogdiag(A):
    """sum(log(diag(A))) per matrix (reference: ``linalg_sumlogdiag``)."""
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("linalg_extractdiag", args=("A",))
def _linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag", args=("A",))
def _linalg_makediag(A, offset=0):
    def mk(v):
        return jnp.diag(v, k=offset)
    for _ in range(A.ndim - 1):
        mk = jax.vmap(mk)
    return mk(A)


@register("linalg_extracttrian", args=("A",))
def _linalg_extracttrian(A, offset=0, lower=True):
    """Flatten the triangular part (reference: ``linalg_extracttrian``)."""
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower \
        else jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


@register("linalg_maketrian", args=("A",))
def _linalg_maketrian(A, offset=0, lower=True):
    k = A.shape[-1]
    # n(n+1)/2 = k for offset 0
    n = int((jnp.sqrt(8 * k + 1) - 1) / 2) if offset == 0 else None
    if n is None:
        raise NotImplementedError("maketrian supports offset=0")
    rows, cols = (jnp.tril_indices(n) if lower else jnp.triu_indices(n))
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return out.at[..., rows, cols].set(A)


@register("linalg_syevd", args=("A",))
def _linalg_syevd(A):
    """Symmetric eigendecomposition; returns (U, L) with A = U^T L U
    rows-as-eigenvectors convention (reference: ``linalg_syevd``)."""
    w, v = jnp.linalg.eigh(A)
    return _t(v), w


@register("linalg_inverse", args=("A",), aliases=("inverse",))
def _linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("linalg_det", args=("A",), aliases=("det",))
def _linalg_det(A):
    return jnp.linalg.det(A)


@register("linalg_slogdet", args=("A",), aliases=("slogdet",))
def _linalg_slogdet(A):
    sign, logabs = jnp.linalg.slogdet(A)
    return sign, logabs


@register("linalg_svd", args=("A",))
def _linalg_svd(A):
    """Thin SVD: returns (UT, L, V) in the reference's convention
    (A = UT^T diag(L) V)."""
    u, s, vh = jnp.linalg.svd(A, full_matrices=False)
    return _t(u), s, vh


@register("moments", args=("data",))
def _moments(data, axes=None, keepdims=False):
    """Mean and variance over ``axes`` (reference: ``moments``)."""
    axes = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=axes, keepdims=keepdims)
    var = jnp.var(data, axis=axes, keepdims=keepdims)
    return mean, var
