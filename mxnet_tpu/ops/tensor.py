"""Tensor operators: elementwise, broadcast, reduce, matrix, indexing, init.

TPU-native re-design of the reference's ``src/operator/tensor/`` tree
(``elemwise_binary_op_basic.cc``, ``elemwise_unary_op_basic.cc``,
``broadcast_reduce_op_value.cc``, ``matrix_op.cc``, ``dot.cc``,
``indexing_op.cc``, ``init_op.cc``, ``ordering_op.cc``).  Every op is a pure
JAX function: XLA fuses elementwise chains and tiles dots onto the MXU, so
there is no hand-written kernel layer (the reference's mshadow expression
templates have no analog here -- ``jax.numpy`` *is* the expression
language).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

# ----------------------------------------------------------------------
# Elementwise binary (broadcasting, numpy semantics). The reference splits
# exact-shape elemwise_* from explicit broadcast_* ops; both map here to the
# same XLA HLO, so the broadcast_* names are aliases.
# ----------------------------------------------------------------------

def _binary(name, fn, aliases=()):
    @register(name, args=("lhs", "rhs"), aliases=aliases)
    def _op(lhs, rhs):
        return fn(lhs, rhs)
    _op.fcompute.__name__ = name
    return _op


_binary("elemwise_add", jnp.add, aliases=("broadcast_add", "broadcast_plus", "_plus"))
_binary("elemwise_sub", jnp.subtract, aliases=("broadcast_sub", "broadcast_minus", "_minus"))
_binary("elemwise_mul", jnp.multiply, aliases=("broadcast_mul", "_mul"))
_binary("elemwise_div", jnp.divide, aliases=("broadcast_div", "_div"))
_binary("broadcast_mod", jnp.mod, aliases=("_mod",))
_binary("broadcast_power", jnp.power, aliases=("_power", "pow"))
_binary("broadcast_maximum", jnp.maximum, aliases=("_maximum", "maximum"))
_binary("broadcast_minimum", jnp.minimum, aliases=("_minimum", "minimum"))
_binary("broadcast_hypot", jnp.hypot)
_binary("broadcast_equal", lambda a, b: (a == b).astype(a.dtype), aliases=("_equal",))
_binary("broadcast_not_equal", lambda a, b: (a != b).astype(a.dtype), aliases=("_not_equal",))
_binary("broadcast_greater", lambda a, b: (a > b).astype(a.dtype), aliases=("_greater",))
_binary("broadcast_greater_equal", lambda a, b: (a >= b).astype(a.dtype), aliases=("_greater_equal",))
_binary("broadcast_lesser", lambda a, b: (a < b).astype(a.dtype), aliases=("_lesser",))
_binary("broadcast_lesser_equal", lambda a, b: (a <= b).astype(a.dtype), aliases=("_lesser_equal",))
_binary("broadcast_logical_and", lambda a, b: jnp.logical_and(a, b).astype(a.dtype))
_binary("broadcast_logical_or", lambda a, b: jnp.logical_or(a, b).astype(a.dtype))
_binary("broadcast_logical_xor", lambda a, b: jnp.logical_xor(a, b).astype(a.dtype))
_binary("arctan2", jnp.arctan2)
_binary("ldexp", lambda a, b: a * (2.0 ** b))


# ----------------------------------------------------------------------
# Elementwise unary (reference: elemwise_unary_op_basic.cc, *_trig.cc).
# ----------------------------------------------------------------------

def _unary(name, fn, aliases=()):
    @register(name, args=("data",), aliases=aliases)
    def _op(data):
        return fn(data)
    return _op


_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("rint", jnp.rint)
_unary("round", jnp.round)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)  # fix == round-toward-zero
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: lax.rsqrt(x))
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("negative", jnp.negative)
_unary("reciprocal", lambda x: 1.0 / x)
_unary("erf", jax.scipy.special.erf)
_unary("erfinv", jax.scipy.special.erfinv)
_unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_unary("gammaln", jax.scipy.special.gammaln)
_unary("logical_not", lambda x: jnp.logical_not(x).astype(x.dtype))
_unary("relu", jax.nn.relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", jax.nn.soft_sign)
_unary("identity", lambda x: x, aliases=("_copy", "stop_gradient_off"))


@register("BlockGrad", args=("data",), aliases=("stop_gradient",))
def _block_grad(data):
    """Stop gradient flow (reference: ``elemwise_unary_op_basic.cc :: BlockGrad``)."""
    return lax.stop_gradient(data)


@register("Cast", args=("data",), aliases=("cast",))
def _cast(data, dtype="float32"):
    """Cast to a new dtype (reference: ``elemwise_unary_op_basic.cc :: Cast``)."""
    return data.astype(jnp.dtype(dtype))


@register("clip", args=("data",))
def _clip(data, a_min=0.0, a_max=1.0):
    """Clip values to ``[a_min, a_max]`` (reference: ``matrix_op.cc :: clip``)."""
    return jnp.clip(data, a_min, a_max)


# scalar forms (reference: elemwise_binary_scalar_op*.cc).  The scalar
# operand adopts the array's dtype (reference semantics: int arrays stay
# int; a bf16 array is not promoted by a Python float).
def _sc(data, scalar):
    return jnp.asarray(scalar).astype(data.dtype)


@register("_plus_scalar", args=("data",))
def _plus_scalar(data, scalar=0.0):
    return data + _sc(data, scalar)


@register("_minus_scalar", args=("data",))
def _minus_scalar(data, scalar=0.0):
    return data - _sc(data, scalar)


@register("_rminus_scalar", args=("data",))
def _rminus_scalar(data, scalar=0.0):
    return _sc(data, scalar) - data


@register("_mul_scalar", args=("data",))
def _mul_scalar(data, scalar=1.0):
    return data * _sc(data, scalar)


@register("_div_scalar", args=("data",))
def _div_scalar(data, scalar=1.0):
    return data / _sc(data, scalar)


@register("_rdiv_scalar", args=("data",))
def _rdiv_scalar(data, scalar=1.0):
    return _sc(data, scalar) / data


@register("_power_scalar", args=("data",))
def _power_scalar(data, scalar=1.0):
    return data ** _sc(data, scalar)


@register("_rpower_scalar", args=("data",))
def _rpower_scalar(data, scalar=1.0):
    return _sc(data, scalar) ** data


@register("_mod_scalar", args=("data",))
def _mod_scalar(data, scalar=1.0):
    return jnp.mod(data, _sc(data, scalar))


@register("_maximum_scalar", args=("data",))
def _maximum_scalar(data, scalar=0.0):
    return jnp.maximum(data, _sc(data, scalar))


@register("_minimum_scalar", args=("data",))
def _minimum_scalar(data, scalar=0.0):
    return jnp.minimum(data, _sc(data, scalar))


@register("_equal_scalar", args=("data",))
def _equal_scalar(data, scalar=0.0):
    return (data == scalar).astype(data.dtype)


@register("_not_equal_scalar", args=("data",))
def _not_equal_scalar(data, scalar=0.0):
    return (data != scalar).astype(data.dtype)


@register("_greater_scalar", args=("data",))
def _greater_scalar(data, scalar=0.0):
    return (data > scalar).astype(data.dtype)


@register("_greater_equal_scalar", args=("data",))
def _greater_equal_scalar(data, scalar=0.0):
    return (data >= scalar).astype(data.dtype)


@register("_lesser_scalar", args=("data",))
def _lesser_scalar(data, scalar=0.0):
    return (data < scalar).astype(data.dtype)


@register("_lesser_equal_scalar", args=("data",))
def _lesser_equal_scalar(data, scalar=0.0):
    return (data <= scalar).astype(data.dtype)


# ----------------------------------------------------------------------
# Reductions (reference: broadcast_reduce_op_value.cc). MXNet's `exclude`
# kwarg reduces over all axes NOT listed.
# ----------------------------------------------------------------------

def _norm_axis(axis, ndim, exclude):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(a for a in range(ndim) if a not in axis)
    return axis


def _reduce(name, fn, aliases=()):
    @register(name, args=("data",), aliases=aliases)
    def _op(data, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis, data.ndim, exclude)
        return fn(data, axis=ax, keepdims=keepdims)
    return _op


_reduce("sum", jnp.sum, aliases=("sum_axis",))
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
_reduce("max", jnp.max, aliases=("max_axis",))
_reduce("min", jnp.min, aliases=("min_axis",))


@register("norm", args=("data",))
def _norm(data, ord=2, axis=None, keepdims=False):
    """Matrix/vector norm (reference: ``broadcast_reduce_op_value.cc :: norm``)."""
    ax = axis if axis is None or isinstance(axis, int) else tuple(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))


@register("argmax", args=("data",))
def _argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin", args=("data",))
def _argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("cumsum", args=("data",))
def _cumsum(data, axis=None, dtype=None):
    return jnp.cumsum(data, axis=axis, dtype=dtype)


@register("logsumexp", args=("data",))
def _logsumexp(data, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(data, axis=axis, keepdims=keepdims)


# ----------------------------------------------------------------------
# Matrix / shape ops (reference: matrix_op.cc, dot.cc).
# ----------------------------------------------------------------------

@register("dot", args=("lhs", "rhs"))
def _dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Tensor dot product (reference: ``src/operator/tensor/dot.cc``).

    2-D x 2-D is a plain matmul on the MXU; higher-rank follows MXNet
    semantics: reduce over the last axis of ``lhs`` and first axis of
    ``rhs``.
    """
    if transpose_a:
        lhs = jnp.moveaxis(lhs, 0, -1) if lhs.ndim > 2 else lhs.T
    if transpose_b:
        rhs = jnp.moveaxis(rhs, -1, 0) if rhs.ndim > 2 else rhs.T
    return jnp.tensordot(lhs, rhs, axes=1)


@register("batch_dot", args=("lhs", "rhs"))
def _batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Batched matmul (reference: ``dot.cc :: batch_dot``)."""
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("transpose", args=("data",))
def _transpose(data, axes=None):
    if axes is None or (isinstance(axes, (tuple, list)) and len(axes) == 0):
        return jnp.transpose(data)
    return jnp.transpose(data, axes)


@register("swapaxes", args=("data",), aliases=("SwapAxis",))
def _swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


def _mx_reshape_infer(src_shape, target):
    """Implement MXNet's reshape special codes 0, -1, -2, -3, -4.

    Reference: ``matrix_op.cc :: ReshapeParam`` / ``InferReshapeShape``.
    0: copy this dim from input; -1: infer; -2: copy all remaining dims;
    -3: merge two consecutive input dims; -4: split one dim into the next
    two target values.
    """
    out = []
    src = list(src_shape)
    i = 0  # position in src
    t = 0
    target = list(target)
    while t < len(target):
        v = target[t]
        if v == 0:
            out.append(src[i]); i += 1
        elif v == -1:
            out.append(-1); i += 1
        elif v == -2:
            out.extend(src[i:]); i = len(src)
        elif v == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif v == -4:
            a, b = target[t + 1], target[t + 2]
            d = src[i]
            if a == -1:
                a = d // b
            if b == -1:
                b = d // a
            out.extend([a, b]); i += 1; t += 2
        else:
            out.append(v); i += 1
        t += 1
    # resolve a single -1
    if out.count(-1) > 1:
        raise MXNetError("reshape: more than one -1 after code expansion")
    return tuple(out)


@register("Reshape", args=("data",), aliases=("reshape",))
def _reshape(data, shape=(), reverse=False):
    """Reshape with MXNet special codes (reference: ``matrix_op.cc :: Reshape``)."""
    if reverse:
        rshape = _mx_reshape_infer(data.shape[::-1], list(shape)[::-1])[::-1]
    else:
        rshape = _mx_reshape_infer(data.shape, shape)
    return jnp.reshape(data, rshape)


@register("reshape_like", args=("lhs", "rhs"))
def _reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register("shape_array", args=("data",))
def _shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int64 if False else jnp.int32)


@register("size_array", args=("data",))
def _size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int32)


@register("expand_dims", args=("data",))
def _expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze", args=("data",))
def _squeeze(data, axis=None):
    return jnp.squeeze(data, axis)


@register("Flatten", args=("data",), aliases=("flatten",))
def _flatten(data):
    """Collapse all but the first axis (reference: ``matrix_op.cc :: Flatten``)."""
    return jnp.reshape(data, (data.shape[0], -1))


@register("reverse", args=("data",), aliases=("flip",))
def _reverse(data, axis=0):
    ax = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, ax)


@register("tile", args=("data",))
def _tile(data, reps=()):
    return jnp.tile(data, reps)


@register("repeat", args=("data",))
def _repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("Pad", args=("data",), aliases=("pad",))
def _pad(data, mode="constant", pad_width=(), constant_value=0.0):
    """N-D padding (reference: ``src/operator/pad.cc``); pad_width is the
    flat MXNet form (before, after) per axis."""
    pw = [(int(pad_width[2 * i]), int(pad_width[2 * i + 1]))
          for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode=jmode, constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


@register("slice", args=("data",))
def _slice(data, begin=(), end=(), step=()):
    """MXNet slice (reference: ``matrix_op.cc :: slice``); None in
    begin/end means full extent."""
    ndim = data.ndim
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step) + [None] * (ndim - len(step)) if step else [None] * ndim
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


@register("slice_axis", args=("data",))
def _slice_axis(data, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like", args=("data", "shape_like"))
def _slice_like(data, shape_like, axes=()):
    axes = tuple(axes) if axes else tuple(range(data.ndim))
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("broadcast_to", args=("data",))
def _broadcast_to(data, shape=()):
    tgt = tuple(s if t == 0 else t for s, t in zip(data.shape, shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like", args=("lhs", "rhs"))
def _broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("broadcast_axis", args=("data",), aliases=("broadcast_axes",))
def _broadcast_axis(data, axis=(), size=()):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("Concat", args=("data",), variadic=True, aliases=("concat",))
def _concat(*data, dim=1):
    """Concatenate along ``dim`` (reference: ``src/operator/nn/concat.cc``)."""
    return jnp.concatenate(data, axis=dim)


@register("stack", args=("data",), variadic=True)
def _stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@register("split", args=("data",), aliases=("SliceChannel",))
def _split(data, num_outputs=1, axis=1, squeeze_axis=False):
    """Split into equal parts (reference: ``slice_channel.cc``)."""
    outs = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs) if num_outputs > 1 else outs[0]


@register("add_n", args=("args",), variadic=True, aliases=("ElementWiseSum",))
def _add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("where", args=("condition", "x", "y"))
def _where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("diag", args=("data",))
def _diag(data, k=0):
    return jnp.diag(data, k) if data.ndim <= 2 else jnp.diagonal(data, k)


@register("L2Normalization", args=("data",))
def _l2_normalization(data, eps=1e-10, mode="instance"):
    """Reference: ``src/operator/l2_normalization.cc``."""
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    else:  # spatial
        ax = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / norm


# ----------------------------------------------------------------------
# Indexing (reference: indexing_op.cc).
# ----------------------------------------------------------------------

@register("take", args=("a", "indices"))
def _take(a, indices, axis=0, mode="clip"):
    """Reference: ``indexing_op.cc :: take``."""
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    return jnp.take(a, indices.astype(jnp.int32), axis=axis, mode=jmode)


@register("pick", args=("data", "index"))
def _pick(data, index, axis=-1, keepdims=False, mode="clip"):
    """Pick per-row elements by index (reference: ``indexing_op.cc :: pick``)."""
    idx = jnp.expand_dims(index.astype(jnp.int32), axis=axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("one_hot", args=("indices",))
def _one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register("gather_nd", args=("data", "indices"))
def _gather_nd(data, indices):
    """Reference: ``indexing_op.cc :: gather_nd``; indices shape (M, ...)."""
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return data[idx]


@register("scatter_nd", args=("data", "indices"))
def _scatter_nd(data, indices, shape=()):
    out = jnp.zeros(shape, dtype=data.dtype)
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return out.at[idx].set(data)


@register("boolean_mask", args=("data", "index"))
def _boolean_mask(data, index, axis=0):
    """Reference: ``contrib/boolean_mask.cc``. Note: output shape is
    data-dependent; not jittable (use `where`-style masking under jit)."""
    return jnp.compress(index.astype(bool), data, axis=axis)


@register("SequenceMask", args=("data", "sequence_length"))
def _sequence_mask(data, sequence_length, use_sequence_length=False, value=0.0, axis=0):
    """Reference: ``src/operator/sequence_mask.cc`` (time-major by default;
    with ``use_sequence_length=False`` the op is identity, as upstream)."""
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    bshape = [1] * data.ndim
    bshape[axis] = maxlen
    steps = steps.reshape(bshape)
    lshape = [1] * data.ndim
    lshape[1 - axis] = sequence_length.shape[0]
    lens = sequence_length.reshape(lshape)
    mask = steps < lens
    return jnp.where(mask, data, value)


@register("SequenceLast", args=("data", "sequence_length"))
def _sequence_last(data, sequence_length, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length.astype(jnp.int32) - 1)
    batch = jnp.arange(data.shape[1 - axis])
    if axis == 0:
        return data[idx, batch]
    return data[batch, idx]


@register("SequenceReverse", args=("data", "sequence_length"))
def _sequence_reverse(data, sequence_length, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis)
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    lens = sequence_length.astype(jnp.int32)
    # reversed index per (time, batch): len-1-t when t < len else t
    rev = jnp.where(steps[:, None] < lens[None, :],
                    lens[None, :] - 1 - steps[:, None], steps[:, None])
    batch = jnp.arange(data.shape[1])
    if axis != 0:
        raise MXNetError("SequenceReverse: only axis=0 (time-major) supported")
    return data[rev, batch[None, :]]


# ----------------------------------------------------------------------
# Ordering (reference: ordering_op.cc).
# ----------------------------------------------------------------------

@register("sort", args=("data",))
def _sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", args=("data",))
def _argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))


@register("topk", args=("data",))
def _topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Reference: ``ordering_op.cc :: topk``."""
    neg = data if not is_ascend else -data
    neg = jnp.moveaxis(neg, axis, -1)
    vals, idx = lax.top_k(neg, k)
    src_vals = jnp.moveaxis(data, axis, -1)
    vals = jnp.take_along_axis(src_vals, idx, axis=-1)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(jnp.dtype(dtype))
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        raise MXNetError("topk ret_typ='mask' not supported")
    raise MXNetError("topk: bad ret_typ %r" % ret_typ)


# ----------------------------------------------------------------------
# Init ops (reference: init_op.cc). These take no tensor inputs.
# ----------------------------------------------------------------------

@register("_zeros", args=())
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(shape, dtype=jnp.dtype(dtype))


@register("_ones", args=())
def _ones(shape=(), dtype="float32"):
    return jnp.ones(shape, dtype=jnp.dtype(dtype))


@register("_full", args=())
def _full(shape=(), value=0.0, dtype="float32"):
    return jnp.full(shape, value, dtype=jnp.dtype(dtype))


@register("_eye", args=())
def _eye(N=1, M=0, k=0, dtype="float32"):
    return jnp.eye(N, M if M else None, k, dtype=jnp.dtype(dtype))


@register("_arange", args=())
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", args=())
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    return jnp.linspace(start, stop, num, endpoint=endpoint, dtype=jnp.dtype(dtype))


@register("zeros_like", args=("data",))
def _zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like", args=("data",))
def _ones_like(data):
    return jnp.ones_like(data)


@register("full_like", args=("data",))
def _full_like(data, fill_value=0.0):
    return jnp.full_like(data, fill_value)


@register("arange_like", args=("data",))
def _arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """Reference: ``contrib/arange_like``; shape-polymorphic arange."""
    if axis is None:
        n = data.size
        shape = data.shape
    else:
        n = data.shape[axis]
        shape = (n,)
    out = start + step * jnp.arange(n, dtype=data.dtype)
    return out.reshape(shape)


# ----------------------------------------------------------------------
# numpy-surface ops (reference: python/mxnet/numpy -- the mx.np world).
# Registered as ops so mx.np functions are tape-aware like everything
# else.
# ----------------------------------------------------------------------

@register("matmul", args=("a", "b"))
def _matmul(a, b):
    return jnp.matmul(a, b)


@register("einsum", args=("data",), variadic=True)
def _einsum(*operands, subscripts=""):
    return jnp.einsum(subscripts, *operands)


@register("tensordot", args=("a", "b"))
def _tensordot(a, b, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(x) if isinstance(x, (list, tuple)) else x
                     for x in axes)
    return jnp.tensordot(a, b, axes=axes)


@register("isnan", args=("data",))
def _isnan(data):
    return jnp.isnan(data)


@register("isinf", args=("data",))
def _isinf(data):
    return jnp.isinf(data)


@register("isfinite", args=("data",))
def _isfinite(data):
    return jnp.isfinite(data)


@register("_np_var", args=("data",))
def _np_var(data, axis=None, ddof=0, keepdims=False):
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.var(data, axis=axis, ddof=ddof, keepdims=keepdims)


@register("_np_std", args=("data",))
def _np_std(data, axis=None, ddof=0, keepdims=False):
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.std(data, axis=axis, ddof=ddof, keepdims=keepdims)


@register("vstack", args=("data",), variadic=True)
def _vstack(*data):
    return jnp.vstack(data)


@register("hstack", args=("data",), variadic=True)
def _hstack(*data):
    return jnp.hstack(data)


@register("dstack", args=("data",), variadic=True)
def _dstack(*data):
    return jnp.dstack(data)
