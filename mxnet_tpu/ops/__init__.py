"""Operator library (registry + definitions).

Importing this package registers every op (reference: static registration
in ``src/operator/*.cc`` via ``NNVM_REGISTER_OP``).
"""
from .registry import OP_REGISTRY, Op, OpParam, get_op, list_ops, register
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import transformer  # noqa: F401
from . import linalg  # noqa: F401
from . import contrib_ops  # noqa: F401
