"""Contrib operators: im2col, quantization, boxes/ROI, CTC (reference:
``src/operator/{im2col,quantization,contrib}``).

TPU notes per family:

- **im2col/col2im** lower to ``lax.conv_general_dilated_patches`` -- the
  same tiling XLA already uses for convolutions.
- **quantization** is int8 *simulation* with fp32 scales (quantize /
  dequantize / requantize + quantized FC).  On TPU the deploy dtype is
  int8-in-bf16-out through the MXU; these ops carry the reference's
  calibration API so quantized graphs port over.
- **boxes** (box_iou, box_nms, ROIPooling, ROIAlign) use static-shape
  masking -- no dynamic gather shapes, scores are suppressed by writing
  -1, exactly the reference's output convention.
- **CTC** exposes the alpha-recursion loss as an *operator* (the layer
  in ``gluon/loss.py`` wraps it).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# ----------------------------------------------------------------------
# im2col / col2im (reference: src/operator/nn/im2col.h)
# ----------------------------------------------------------------------

def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _im2col_impl(data, kernel, stride, dilate, pad):
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    dh, dw = _pair(dilate)
    ph, pw = _pair(pad)
    patches = lax.conv_general_dilated_patches(
        data, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, ckk, oh, ow = patches.shape
    return patches.reshape(n, ckk, oh * ow)


@register("im2col", args=("data",))
def _im2col(data, kernel=(3, 3), stride=(1, 1), dilate=(1, 1),
            pad=(0, 0)):
    """(N, C, H, W) -> (N, C*kh*kw, L) patches (reference: ``im2col``)."""
    return _im2col_impl(data, kernel, stride, dilate, pad)


@register("col2im", args=("data",))
def _col2im(data, output_size=(0, 0), kernel=(3, 3), stride=(1, 1),
            dilate=(1, 1), pad=(0, 0)):
    """Scatter-add patches back to (N, C, H, W) (reference: ``col2im``);
    the linear adjoint of im2col, expressed as its vjp so the two stay
    exact inverses-in-adjoint."""
    oh, ow = _pair(output_size)
    kh, kw = _pair(kernel)
    n = data.shape[0]
    c = data.shape[1] // (kh * kw)

    def fwd(img):
        return _im2col_impl(img, (kh, kw), _pair(stride), _pair(dilate),
                            _pair(pad))

    zero = jnp.zeros((n, c, oh, ow), data.dtype)
    _, vjp = jax.vjp(fwd, zero)
    (img,) = vjp(data)
    return img


# ----------------------------------------------------------------------
# Quantization (reference: src/operator/quantization/*.cc)
# ----------------------------------------------------------------------

@register("quantize_v2", args=("data",),
          aliases=("_contrib_quantize_v2",))
def _quantize_v2(data, out_type="int8", min_calib_range=None,
                 max_calib_range=None):
    """fp32 -> int8 + (min, max) calibration range (reference:
    ``quantize_v2``)."""
    if min_calib_range is None or max_calib_range is None:
        amin = jnp.min(data)
        amax = jnp.max(data)
    else:
        amin = jnp.asarray(min_calib_range, jnp.float32)
        amax = jnp.asarray(max_calib_range, jnp.float32)
    bound = jnp.maximum(jnp.abs(amin), jnp.abs(amax))
    scale = 127.0 / jnp.maximum(bound, 1e-20)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, -bound, bound


@register("quantize", args=("data", "min_range", "max_range"))
def _quantize(data, min_range, max_range, out_type="int8"):
    bound = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    scale = 127.0 / jnp.maximum(bound, 1e-20)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, -bound, bound


@register("dequantize", args=("data", "min_range", "max_range"))
def _dequantize(data, min_range, max_range, out_type="float32"):
    bound = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    # divisor follows the storage dtype: int8 spans +-127, an int32
    # accumulator from a quantized matmul spans +-127*127 by convention
    q_max = 127.0 if data.dtype == jnp.int8 else 127.0 * 127.0
    return data.astype(jnp.float32) * (bound / q_max)


@register("requantize", args=("data", "min_range", "max_range"),
          aliases=("_contrib_requantize",))
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None):
    """int32 accum -> int8 with a new range (reference: ``requantize``)."""
    real = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
        / (127.0 * 127.0))
    if min_calib_range is not None:
        bound = max(abs(float(min_calib_range)),
                    abs(float(max_calib_range)))
        bound = jnp.asarray(bound, jnp.float32)
    else:
        bound = jnp.maximum(jnp.abs(real).max(), 1e-20)
    q = jnp.clip(jnp.round(real * (127.0 / bound)), -127, 127) \
        .astype(jnp.int8)
    return q, -bound, bound


@register("quantized_fully_connected",
          args=("data", "weight", "bias", "min_data", "max_data",
                "min_weight", "max_weight", "min_bias", "max_bias"))
def _quantized_fully_connected(data, weight, bias, min_data, max_data,
                               min_weight, max_weight, min_bias, max_bias,
                               num_hidden=0, no_bias=False, flatten=True):
    """int8 x int8 -> int32 FC (reference:
    ``quantized_fully_connected``).  On TPU the int8 matmul rides the
    MXU via int32 accumulation."""
    x = data.astype(jnp.int32)
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    acc = jax.lax.dot_general(
        x, weight.astype(jnp.int32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    sd = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) / 127.0
    sw = jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight)) / 127.0
    if bias is not None and not no_bias:
        sb = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias)) / 127.0
        scale_ratio = sb / jnp.maximum(sd * sw, 1e-20)
        acc = acc + jnp.round(
            bias.astype(jnp.float32) * scale_ratio).astype(jnp.int32)
    out_bound = 127.0 * 127.0 * sd * sw
    return acc, -out_bound, out_bound


@register("quantized_conv",
          args=("data", "weight", "bias", "min_data", "max_data",
                "min_weight", "max_weight", "min_bias", "max_bias"),
          aliases=("_contrib_quantized_conv",))
def _quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                    max_weight, min_bias, max_bias, kernel=(), stride=(),
                    dilate=(), pad=(), num_filter=0, num_group=1,
                    no_bias=True, layout="NCHW"):
    """int8 x int8 -> int32 convolution (reference:
    ``quantized_conv``).  The int8 contraction rides the MXU with an
    int32 accumulator (``preferred_element_type``); output carries the
    (min, max) range convention of the quantized family."""
    from .nn import _conv_dnums, _pair as _p
    nsp = data.ndim - 2
    stride = _p(stride, nsp) if stride else (1,) * nsp
    dilate = _p(dilate, nsp) if dilate else (1,) * nsp
    pad = _p(pad, nsp) if pad else (0,) * nsp
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    _conv_dnums(data.ndim, layout))
    acc = lax.conv_general_dilated(
        data.astype(jnp.int32), weight.astype(jnp.int32),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    sd = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) / 127.0
    sw = jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight)) / 127.0
    if bias is not None and not no_bias:
        from .nn import _bias_bshape
        sb = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias)) / 127.0
        scale_ratio = sb / jnp.maximum(sd * sw, 1e-20)
        bshape = _bias_bshape(data.ndim, layout)
        acc = acc + jnp.round(bias.astype(jnp.float32).reshape(bshape)
                              * scale_ratio).astype(jnp.int32)
    out_bound = 127.0 * 127.0 * sd * sw
    return acc, -out_bound, out_bound


@register("quantized_pooling", args=("data", "min_data", "max_data"),
          aliases=("_contrib_quantized_pooling",))
def _quantized_pooling(data, min_data, max_data, kernel=(), pool_type="max",
                       stride=(), pad=(), global_pool=False,
                       count_include_pad=True,
                       pooling_convention="valid", layout="NCHW"):
    """int8 pooling passthrough (reference: ``quantized_pooling``): pool
    in the integer domain, range unchanged."""
    from .registry import get_op
    _pooling = get_op("Pooling").fcompute
    out = _pooling(data.astype(jnp.float32), kernel=kernel,
                   pool_type=pool_type, stride=stride, pad=pad,
                   global_pool=global_pool,
                   count_include_pad=count_include_pad,
                   pooling_convention=pooling_convention, layout=layout)
    return jnp.round(out).astype(data.dtype), min_data, max_data


# ----------------------------------------------------------------------
# Boxes / ROI (reference: src/operator/contrib/{bounding_box,roi_align}.cc,
# src/operator/roi_pooling.cc)
# ----------------------------------------------------------------------

def _iou_matrix(a, b, fmt="corner"):
    if fmt == "center":
        def to_corner(x):
            cx, cy, w, h = (x[..., 0], x[..., 1], x[..., 2], x[..., 3])
            return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                              cy + h / 2], axis=-1)
        a, b = to_corner(a), to_corner(b)
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0) * \
        jnp.maximum(a[..., 3] - a[..., 1], 0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0)
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


@register("box_iou", args=("lhs", "rhs"),
          aliases=("_contrib_box_iou",))
def _box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU (reference: ``_contrib_box_iou``)."""
    return _iou_matrix(lhs, rhs, format)


@register("box_nms", args=("data",),
          aliases=("_contrib_box_nms",))
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1, force_suppress=True,
             in_format="corner", out_format="corner"):
    """Non-max suppression with static shapes (reference:
    ``_contrib_box_nms``): suppressed entries get score -1, order is
    score-sorted, shape is unchanged -- no dynamic output sizes."""
    def one(batch):
        scores = batch[:, score_index]
        boxes = batch[:, coord_start:coord_start + 4]
        order = jnp.argsort(-scores)
        boxes_s = boxes[order]
        scores_s = scores[order]
        n = scores.shape[0]
        iou = _iou_matrix(boxes_s, boxes_s, in_format)

        def body(i, keep):
            # suppress j>i overlapping a kept i
            sup = (iou[i] > overlap_thresh) & \
                (jnp.arange(n) > i) & keep[i]
            return keep & ~sup
        keep = lax.fori_loop(0, n, body, scores_s > valid_thresh)
        out = batch[order]
        out = out.at[:, score_index].set(
            jnp.where(keep, scores_s, -1.0))
        return out
    if data.ndim == 2:
        return one(data)
    return jax.vmap(one)(data)


@register("ROIPooling", args=("data", "rois"))
def _roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max-pool each ROI to a fixed grid (reference:
    ``src/operator/roi_pooling.cc``).  Static shapes: every ROI yields
    (C, ph, pw) by masked max over the feature map."""
    ph, pw = _pair(pooled_size)
    n, c, h, w = data.shape

    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = jnp.round(roi[1:5] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bh, bw = rh / ph, rw / pw
        fmap = data[bidx]                       # (C, H, W)

        def cell(py, px):
            ys0 = y1 + py * bh
            ys1 = y1 + (py + 1) * bh
            xs0 = x1 + px * bw
            xs1 = x1 + (px + 1) * bw
            my = (ys >= jnp.floor(ys0)) & (ys < jnp.ceil(ys1))
            mxm = (xs >= jnp.floor(xs0)) & (xs < jnp.ceil(xs1))
            mask = my[:, None] & mxm[None, :]
            neg = jnp.full((h, w), -jnp.inf, fmap.dtype)
            sel = jnp.where(mask[None], fmap, neg[None])
            out = jnp.max(sel, axis=(1, 2))
            return jnp.where(jnp.isfinite(out), out, 0.0)
        grid = jnp.stack([jnp.stack([cell(py, px) for px in range(pw)],
                                    axis=-1) for py in range(ph)], axis=-2)
        return grid                              # (C, ph, pw)
    return jax.vmap(one)(rois)


@register("ROIAlign", args=("data", "rois"),
          aliases=("_contrib_ROIAlign",))
def _roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               sample_ratio=2):
    """Bilinear ROI align (reference: ``contrib/roi_align.cc``)."""
    ph, pw = _pair(pooled_size)
    n, c, h, w = data.shape
    sr = max(int(sample_ratio), 1)

    def bilinear(fmap, y, x):
        y0 = jnp.clip(jnp.floor(y), 0, h - 1)
        x0 = jnp.clip(jnp.floor(x), 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy1 = y - y0
        wx1 = x - x0
        y0i, x0i, y1i, x1i = (a.astype(jnp.int32) for a in
                              (y0, x0, y1, x1))
        return (fmap[:, y0i, x0i] * (1 - wy1) * (1 - wx1) +
                fmap[:, y1i, x0i] * wy1 * (1 - wx1) +
                fmap[:, y0i, x1i] * (1 - wy1) * wx1 +
                fmap[:, y1i, x1i] * wy1 * wx1)

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1:5] * spatial_scale
        bh = jnp.maximum(y2 - y1, 1.0) / ph
        bw = jnp.maximum(x2 - x1, 1.0) / pw
        fmap = data[bidx]

        def cell(py, px):
            acc = 0.0
            for iy in range(sr):
                for ix in range(sr):
                    y = y1 + (py + (iy + 0.5) / sr) * bh
                    x = x1 + (px + (ix + 0.5) / sr) * bw
                    acc = acc + bilinear(fmap, y, x)
            return acc / (sr * sr)
        return jnp.stack([jnp.stack([cell(py, px) for px in range(pw)],
                                    axis=-1) for py in range(ph)], axis=-2)
    return jax.vmap(one)(rois)


# ----------------------------------------------------------------------
# CTC as an operator (reference: src/operator/nn/ctc_loss.cc)
# ----------------------------------------------------------------------

@register("CTCLoss", args=("data", "label"), aliases=("ctc_loss",))
def _ctc_loss(data, label, use_data_lengths=False, use_label_lengths=False,
              blank_label="first"):
    """Connectionist temporal classification loss op over (T, N, C)
    activations and (N, L) labels (reference: ``CTCLoss``).  The gluon
    layer (``gluon/loss.py :: CTCLoss``) wraps this with layout/length
    options; the op itself implements the log-space alpha recursion via
    ``lax.scan``."""
    T, N, C = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    blank = 0 if blank_label == "first" else C - 1
    lab = label.astype(jnp.int32)
    L = lab.shape[1]
    # extended label sequence: blank l1 blank l2 ... blank
    S = 2 * L + 1
    ext = jnp.full((N, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    valid = jnp.concatenate(
        [jnp.ones((N, 1), jnp.bool_),
         jnp.repeat(lab >= 0, 2, axis=1)], axis=1)[:, :S]
    ext = jnp.where(valid, ext, blank)
    label_len = jnp.sum(lab >= 0, axis=1)

    neg_inf = -1e30
    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0][jnp.arange(N), ext[:, 0]])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_len > 0,
                  logp[0][jnp.arange(N), ext[:, 1]], neg_inf))

    same = jnp.concatenate(
        [jnp.zeros((N, 2), jnp.bool_), ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, logp_t):
        a0 = alpha
        a1 = jnp.concatenate(
            [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate(
            [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        a2 = jnp.where(same, neg_inf, a2)
        m = jnp.maximum(jnp.maximum(a0, a1), a2)
        m_safe = jnp.maximum(m, neg_inf)
        summed = jnp.exp(a0 - m_safe) + jnp.exp(a1 - m_safe) + \
            jnp.exp(a2 - m_safe)
        new = m_safe + jnp.log(summed) + \
            logp_t[jnp.arange(N)[:, None], ext]
        return new, None

    alpha, _ = lax.scan(step, alpha0, logp[1:])
    end = 2 * label_len - 1
    last_blank = alpha[jnp.arange(N), 2 * label_len]
    last_label = alpha[jnp.arange(N),
                       jnp.maximum(end, 0)]
    # empty label sequence: only the all-blank path exists; the clamped
    # end index would double-count alpha[:, 0]
    last_label = jnp.where(label_len == 0, neg_inf, last_label)
    m = jnp.maximum(last_blank, last_label)
    ll = m + jnp.log(jnp.exp(last_blank - m) + jnp.exp(last_label - m))
    return -ll
