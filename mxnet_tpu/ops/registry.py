"""Self-describing operator registry.

TPU-native re-design of the reference's NNVM op registration + dmlc
parameter system (reference: ``include/mxnet/op_attr_types.h :: FCompute``,
``NNVM_REGISTER_OP`` in ``src/operator/``, ``3rdparty/dmlc-core/include/
dmlc/parameter.h :: DMLC_DECLARE_PARAMETER``).

Key differences from the reference, by design:

- An op's compute function is a pure JAX function over ``jax.Array``s.  XLA
  is the kernel library; there is no per-device FCompute dispatch table --
  the same definition lowers to TPU (MXU/VPU) or CPU.
- Gradients come from ``jax.vjp`` over the compute function, replacing the
  reference's hand-written ``FGradient`` registrations, except where an op
  registers a ``jax.custom_vjp`` itself (e.g. SoftmaxOutput).
- Shape/type inference (``FInferShape``/``FInferType``) is
  ``jax.eval_shape`` over the compute function -- exact by construction.
- The typed parameter list is introspected from the compute function's
  keyword signature, and Python wrappers for ``mx.nd.*`` / ``mx.sym.*`` are
  generated from it at import time, preserving the reference's
  self-describing API property (``python/mxnet/ndarray/register.py ::
  _make_ndarray_function``).
"""
from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, build_param_doc

__all__ = ["Op", "OpParam", "register", "get_op", "list_ops", "OP_REGISTRY"]


@dataclass
class OpParam:
    """One typed config parameter of an op (dmlc::Parameter field analog)."""
    name: str
    default: Any = None
    has_default: bool = True
    doc: str = ""

    @property
    def type_str(self) -> str:
        if self.default is None:
            return "any"
        return type(self.default).__name__


@dataclass
class Op:
    """A registered operator.

    ``fcompute(*tensor_args, **params) -> jax.Array | tuple`` is the single
    source of truth: eager dispatch, jit tracing, vjp, and shape inference
    all go through it.
    """
    name: str
    fcompute: Callable
    arg_names: Tuple[str, ...]
    variadic: bool = False
    params: List[OpParam] = field(default_factory=list)
    doc: str = ""
    aliases: Tuple[str, ...] = ()
    # Number of leading tensor outputs that are differentiable; the rest
    # (e.g. BatchNorm's updated running stats) are carried states.
    num_diff_outputs: Optional[int] = None
    # Ops flagged stateful_rng consume an implicit PRNG key (dropout, random
    # samplers) -- the hybridize tracer threads a key input for them.
    stateful_rng: bool = False

    def param_defaults(self) -> Dict[str, Any]:
        return {p.name: p.default for p in self.params if p.has_default}

    def __repr__(self):
        return "Op(%s)" % self.name


OP_REGISTRY: Dict[str, Op] = {}


def register(name: str, args: Sequence[str] = ("data",), variadic: bool = False,
             aliases: Sequence[str] = (), num_diff_outputs: Optional[int] = None,
             stateful_rng: bool = False):
    """Decorator registering a JAX compute function as a framework op.

    The decorated function's positional parameters must match ``args`` (the
    tensor inputs; or ``*data`` when ``variadic``), and every keyword
    parameter with a default becomes a typed op param surfaced in the
    generated ``mx.nd.*`` signature and docstring.
    """
    def deco(fn: Callable) -> Op:
        sig = inspect.signature(fn)
        params = []
        seen_args = []
        for pname, p in sig.parameters.items():
            if p.kind == inspect.Parameter.VAR_POSITIONAL:
                continue
            if stateful_rng and pname == "key":
                continue  # injected PRNG key, not a user-facing param
            if p.default is inspect.Parameter.empty and pname in args:
                seen_args.append(pname)
                continue
            if p.default is inspect.Parameter.empty and not variadic:
                # required keyword param (e.g. shape for init ops)
                params.append(OpParam(pname, None, has_default=False))
            else:
                params.append(OpParam(pname, p.default, has_default=True))
        if not variadic and tuple(seen_args) != tuple(args):
            raise MXNetError(
                "op %s: positional args %r do not match declared %r"
                % (name, seen_args, tuple(args)))
        op = Op(name=name, fcompute=fn, arg_names=tuple(args),
                variadic=variadic, params=params,
                doc=inspect.getdoc(fn) or "", aliases=tuple(aliases),
                num_diff_outputs=num_diff_outputs, stateful_rng=stateful_rng)
        op.doc = (op.doc + "\n\n" + build_param_doc(params)) if params else op.doc
        if name in OP_REGISTRY:
            raise MXNetError(
                "duplicate op registration: %r is already registered "
                "as %r; pick a distinct name or register an alias on "
                "the existing op" % (name, OP_REGISTRY[name].name))
        OP_REGISTRY[name] = op
        for a in aliases:
            # an alias silently shadowing another op would make graph
            # dispatch depend on import order -- reject it loudly
            if a in OP_REGISTRY and OP_REGISTRY[a] is not op:
                raise MXNetError(
                    "duplicate op alias registration: %r on op %r is "
                    "already bound to op %r" % (a, name,
                                                OP_REGISTRY[a].name))
            OP_REGISTRY[a] = op
        return op
    return deco


def get_op(name: str) -> Op:
    try:
        return OP_REGISTRY[name]
    except KeyError:
        import difflib
        close = difflib.get_close_matches(str(name), OP_REGISTRY, n=3,
                                          cutoff=0.6)
        hint = "; did you mean %s?" % " or ".join(repr(c) for c in close) \
            if close else " (see mxnet_tpu.ops.list_ops())"
        raise MXNetError("unknown operator %r%s" % (name, hint)) from None


def list_ops() -> List[str]:
    return sorted(OP_REGISTRY)
