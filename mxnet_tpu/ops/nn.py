"""Neural-network operators.

TPU-native re-design of the reference's ``src/operator/nn/`` tree
(``convolution.cc``, ``fully_connected.cc``, ``batch_norm.cc``,
``pooling.cc``, ``activation.cc``, ``softmax.cc``, ``layer_norm.cc``,
``dropout.cc``, ``deconvolution.cc``, ``upsampling.cc``) and the cuDNN
variants under ``src/operator/nn/cudnn/``.  On TPU the "cuDNN fast path" is
XLA itself: convs and matmuls lower to MXU ops, normalization/activation
chains fuse into them.  Stateful-looking ops are functional here:

- BatchNorm *returns* updated running stats (``num_diff_outputs=1``); the
  Gluon layer rebinds its aux parameters (the reference mutates aux states
  in-place via the engine's mutable vars).
- Dropout and random samplers are ``stateful_rng``: the dispatcher injects
  a PRNG key as the first argument (the reference draws from the per-device
  ResourceManager RNG, ``src/resource.cc``).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# ----------------------------------------------------------------------
# Dense / conv / pooling
# ----------------------------------------------------------------------

@register("FullyConnected", args=("data", "weight", "bias"))
def _fully_connected(data, weight, bias, num_hidden=0, no_bias=False, flatten=True):
    """Dense layer (reference: ``src/operator/nn/fully_connected.cc``).

    weight has shape (num_hidden, in_units) as in the reference; the matmul
    contracts data's trailing axis with weight's trailing axis (MXU-friendly
    single dot_general).
    """
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = lax.dot_general(data, weight, (((data.ndim - 1,), (1,)), ((), ())))
    if not no_bias and bias is not None:
        out = out + bias
    return out


_DEFAULT_LAYOUTS = {3: "NCH", 4: "NCHW", 5: "NCDHW"}


def _conv_dnums(ndim, layout=None):
    """Dimension-number strings for a data layout.

    The weight layout follows the reference's convention: the data layout
    string with N->O and C->I (NCHW -> OIHW, NHWC -> OHWI, ...).
    """
    if not layout:
        layout = _DEFAULT_LAYOUTS.get(ndim)
    if layout is None or len(layout) != ndim:
        raise MXNetError("Convolution: unsupported input rank %d / layout %r"
                         % (ndim, layout))
    rhs = layout.replace("N", "O").replace("C", "I")
    return (layout, rhs, layout)


def _bias_bshape(ndim, layout):
    c_axis = layout.index("C") if layout else 1
    shape = [1] * ndim
    shape[c_axis] = -1
    return tuple(shape)


@register("Convolution", args=("data", "weight", "bias"))
def _convolution(data, weight, bias, kernel=(), stride=(), dilate=(), pad=(),
                 num_filter=0, num_group=1, no_bias=False, layout="NCHW"):
    """N-D convolution (reference: ``src/operator/nn/convolution.cc``).

    Lowers to one ``lax.conv_general_dilated`` -- XLA tiles it onto the MXU
    (the reference dispatches to cuDNN ``cudnn_convolution-inl.h``).

    ``layout`` follows the reference's semantics: it names the data (and
    derived weight) layout, e.g. NCHW (weight OIHW) or NHWC (weight OHWI).
    On TPU channels-last is the fast path -- the channel dim lands in the
    128-wide lane dimension of the (8, 128) vector tiles, so 56x56
    activations don't pad the 128-lane minor dim the way W=56 does in
    NCHW.
    """
    nsp = data.ndim - 2
    stride = _pair(stride, nsp) if stride else (1,) * nsp
    dilate = _pair(dilate, nsp) if dilate else (1,) * nsp
    pad = _pair(pad, nsp) if pad else (0,) * nsp
    if layout and len(layout) != data.ndim:
        layout = _DEFAULT_LAYOUTS.get(data.ndim)
    if not jnp.issubdtype(data.dtype, jnp.floating) and \
            jnp.issubdtype(weight.dtype, jnp.floating):
        # uint8 image batches convolve in the weight dtype (the pipeline
        # ships uint8 to the device and casts there -- 4x less transfer)
        data = data.astype(weight.dtype)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    _conv_dnums(data.ndim, layout))
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape(_bias_bshape(data.ndim, layout))
    return out


@register("Deconvolution", args=("data", "weight", "bias"))
def _deconvolution(data, weight, bias, kernel=(), stride=(), dilate=(), pad=(),
                   adj=(), num_filter=0, num_group=1, no_bias=True, layout="NCHW"):
    """Transposed convolution (reference: ``deconvolution.cc``).

    Implemented as the gradient of Convolution (lhs-dilated conv), matching
    the reference's definition.  Weight shape (in_c, out_c/groups, *k).
    """
    nsp = data.ndim - 2
    if layout and len(layout) == data.ndim \
            and layout.index("C") == data.ndim - 1:
        # channels-last: run the channels-first path on transposed operands
        # (deconv is never the hot op; correctness over layout tuning)
        perm = (0, data.ndim - 1) + tuple(range(1, data.ndim - 1))
        inv = (0,) + tuple(range(2, data.ndim)) + (1,)
        wperm = (0, weight.ndim - 1) + tuple(range(1, weight.ndim - 1))
        out = _deconv_channels_first(
            jnp.transpose(data, perm), jnp.transpose(weight, wperm), bias,
            stride=stride, dilate=dilate, pad=pad, adj=adj,
            num_group=num_group, no_bias=no_bias)
        return jnp.transpose(out, inv)
    return _deconv_channels_first(data, weight, bias, stride=stride,
                                  dilate=dilate, pad=pad, adj=adj,
                                  num_group=num_group, no_bias=no_bias)


def _deconv_channels_first(data, weight, bias, stride=(), dilate=(), pad=(),
                           adj=(), num_group=1, no_bias=True):
    nsp = data.ndim - 2
    stride = _pair(stride, nsp) if stride else (1,) * nsp
    dilate = _pair(dilate, nsp) if dilate else (1,) * nsp
    pad = _pair(pad, nsp) if pad else (0,) * nsp
    adj = _pair(adj, nsp) if adj else (0,) * nsp
    k = weight.shape[2:]
    # effective kernel extent
    keff = [d * (kk - 1) + 1 for kk, d in zip(k, dilate)]
    padding = [(keff[i] - 1 - pad[i], keff[i] - 1 - pad[i] + adj[i])
               for i in range(nsp)]
    # flip spatial dims, swap I/O channels
    w = jnp.flip(weight, axis=tuple(range(2, weight.ndim)))
    if num_group > 1:
        ic = weight.shape[0]
        w = w.reshape((num_group, ic // num_group) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((num_group * w.shape[1], ic // num_group) + w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    dn = lax.conv_dimension_numbers(data.shape, w.shape, _conv_dnums(data.ndim))
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nsp, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nsp)
    return out


@register("Pooling", args=("data",))
def _pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
             global_pool=False, count_include_pad=True,
             pooling_convention="valid", layout="NCHW"):
    """Max/avg/sum/lp pooling (reference: ``src/operator/nn/pooling.cc``).

    ``layout`` names the data layout (NCHW/NHWC/...); the pooling window
    spans its spatial dims.
    """
    nsp = data.ndim - 2
    if not layout or len(layout) != data.ndim:
        layout = _DEFAULT_LAYOUTS.get(data.ndim, "NCHW")
    sp_axes = [i for i, c in enumerate(layout) if c not in ("N", "C")]
    sp_sizes = [data.shape[i] for i in sp_axes]
    if global_pool:
        kernel = tuple(sp_sizes)
        stride = (1,) * nsp
        pad = (0,) * nsp
    else:
        kernel = _pair(kernel, nsp)
        stride = _pair(stride, nsp) if stride else (1,) * nsp
        pad = _pair(pad, nsp) if pad else (0,) * nsp
    window = [1] * data.ndim
    strides = [1] * data.ndim
    padding = [(0, 0)] * data.ndim
    for j, ax in enumerate(sp_axes):
        window[ax] = kernel[j]
        strides[ax] = stride[j]
        padding[ax] = (pad[j], pad[j])
    if pooling_convention == "full":
        # ceil-mode: extend right/bottom padding so ragged edges are kept
        for j, ax in enumerate(sp_axes):
            size = sp_sizes[j] + 2 * pad[j] - kernel[j]
            rem = size % stride[j]
            extra = stride[j] - rem if rem else 0
            padding[ax] = (pad[j], pad[j] + extra)
    window = tuple(window)
    strides = tuple(strides)
    padding = tuple(padding)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            return summed / float(np.prod(kernel))
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        p = 2.0
        s = lax.reduce_window(jnp.abs(data) ** p, 0.0, lax.add, window, strides, padding)
        return s ** (1.0 / p)
    raise MXNetError("Pooling: bad pool_type %r" % pool_type)


@register("UpSampling", args=("data",), variadic=True)
def _upsampling(*data, scale=1, sample_type="nearest", num_args=1):
    """Reference: ``src/operator/upsampling.cc`` (nearest mode)."""
    x = data[0]
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        return out
    return jax.image.resize(
        x, x.shape[:2] + (x.shape[2] * scale, x.shape[3] * scale), "bilinear")


@register("BilinearResize2D", args=("data",))
def _bilinear_resize(data, height=0, width=0, scale_height=None, scale_width=None):
    """Reference: ``contrib/bilinear_resize.cc``."""
    h = int(data.shape[2] * scale_height) if scale_height else height
    w = int(data.shape[3] * scale_width) if scale_width else width
    return jax.image.resize(data, data.shape[:2] + (h, w), "bilinear")


# ----------------------------------------------------------------------
# Normalization
# ----------------------------------------------------------------------

@register("BatchNorm", args=("data", "gamma", "beta", "moving_mean", "moving_var"),
          num_diff_outputs=1)
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                axis=1, output_mean_var=False, training=False):
    """Batch normalization (reference: ``src/operator/nn/batch_norm.cc``).

    Functional form: returns ``(out, new_moving_mean, new_moving_var)``.
    The reference mutates the moving stats through the engine's mutable
    aux vars; here the Gluon BatchNorm layer rebinds its aux Parameters
    with the returned values (and the hybridize tracer threads them as
    loop-carried state).
    """
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    # Stats accumulate in fp32 even for bf16 activations (AMP): the
    # upcast fuses into the reduction, so activations stay bf16 in HBM
    # while the mean/var math is exact enough.
    xf = data.astype(jnp.float32)
    if training and not use_global_stats:
        # Shifted one-pass moments: E[(x-c)^2] - E[x-c]^2 with the
        # per-channel shift c = moving_mean.  The two reductions are
        # independent, so XLA fuses them into ONE read pass over the
        # activation (jnp.var's (x - mean)^2 form depends on the mean
        # and forces a second pass); the shift bounds the catastrophic
        # cancellation of the naive E[x^2]-E[x]^2 form when |mean| >>
        # std (large-offset inputs), since moving_mean tracks the batch
        # mean and |E[x-c]| stays near zero in steady state.
        c = lax.stop_gradient(moving_mean.astype(jnp.float32)) \
            .reshape(bshape)
        y = xf - c
        mean_y = jnp.mean(y, axis=reduce_axes)
        m2 = jnp.mean(y * y, axis=reduce_axes)
        var = jnp.maximum(m2 - mean_y * mean_y, 0.0)
        mean = mean_y + c.reshape(mean_y.shape)
        # EMA blended in fp32, stored back at the aux dtype: with bf16
        # running stats the weak-typed ``momentum * moving_mean``
        # product would round at bf16 (8 mantissa bits) every step,
        # and (1 - momentum) = 0.1-ish deltas drop below the store's
        # resolution after a few hundred steps.
        new_mean = (momentum * moving_mean.astype(jnp.float32)
                    + (1 - momentum) * mean).astype(moving_mean.dtype)
        new_var = (momentum * moving_var.astype(jnp.float32)
                   + (1 - momentum) * var).astype(moving_var.dtype)
    else:
        # eval path: upcast BEFORE the eps add -- in bf16,
        # var + 1e-5 == var exactly, and rsqrt would run at 8 mantissa
        # bits
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        new_mean, new_var = moving_mean, moving_var
    inv = (lax.rsqrt(var + eps) * g).astype(jnp.float32)
    out = (xf - mean.reshape(bshape).astype(jnp.float32)) \
        * inv.reshape(bshape) + beta.reshape(bshape).astype(jnp.float32)
    return (out.astype(data.dtype), lax.stop_gradient(new_mean),
            lax.stop_gradient(new_var))


@register("fused_batch_norm_relu",
          args=("data", "gamma", "beta", "moving_mean", "moving_var"),
          num_diff_outputs=1)
def _fused_batch_norm_relu(data, gamma, beta, moving_mean, moving_var,
                           eps=1e-5, momentum=0.9, fix_gamma=True,
                           use_global_stats=False, axis=1,
                           training=False):
    """Fused BatchNorm+ReLU (kernel tier, docs/kernels.md): same
    functional contract as ``BatchNorm`` -- returns ``(out,
    new_moving_mean, new_moving_var)`` -- with the relu epilogue fused
    into the normalize pass.  Kernel-vs-XLA selection happens ONCE in
    the registry (``kernels.choose('fused_bn_relu')``): the Pallas VMEM
    kernel on TPU (channels-last inputs; interpret mode on CPU under
    MXNET_TPU_KERNELS=1), ``relu(BatchNorm(...))`` otherwise.  The
    gluon ``HybridSequential`` BatchNorm+Activation fusion sites
    dispatch here when the tier is armed."""
    from ..kernels.fused_bn_relu import fused_bn_relu as _fused
    return _fused(data, gamma, beta, moving_mean, moving_var, eps=eps,
                  momentum=momentum, fix_gamma=fix_gamma,
                  use_global_stats=use_global_stats, axis=axis,
                  training=training)


def _ln_xla_lastaxis(data, gamma, beta, eps):
    xf = data.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    out = out * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    return out.astype(data.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_pallas(data, gamma, beta, eps):
    # forward = fused VMEM kernel; backward = XLA math (recompute), the
    # same pattern as the flash-attention op -- pallas_call has no
    # transpose rule, so the custom_vjp keeps the op differentiable
    from .pallas.layernorm import layernorm_fwd_pallas
    shape = data.shape
    out2d = layernorm_fwd_pallas(data.reshape(-1, shape[-1]), gamma,
                                 beta, eps=eps)
    return out2d.reshape(shape)


def _ln_pallas_fwd(data, gamma, beta, eps):
    return _ln_pallas(data, gamma, beta, eps), (data, gamma, beta)


def _ln_pallas_bwd(eps, res, g):
    data, gamma, beta = res
    _, vjp = jax.vjp(lambda d, ga, be: _ln_xla_lastaxis(d, ga, be, eps),
                     data, gamma, beta)
    return vjp(g)


_ln_pallas.defvjp(_ln_pallas_fwd, _ln_pallas_bwd)


@register("LayerNorm", args=("data", "gamma", "beta"))
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, use_pallas=False):
    """Layer normalization (reference: ``src/operator/nn/layer_norm.cc``).

    Default path is written so XLA fuses the whole thing into one
    elementwise pass; ``use_pallas=True`` selects the explicit fused
    VMEM kernel (``ops/pallas/layernorm.py``) for last-axis
    normalization.  Stats accumulate in fp32 for bf16 activations.
    """
    if use_pallas and axis in (-1, data.ndim - 1):
        from .pallas import layernorm as _pln
        if _pln._HAS_PALLAS:
            try:
                return _ln_pallas(data, gamma, beta, float(eps))
            except Exception:
                # backend without compiled-pallas support (e.g. CPU):
                # fall through to the XLA path
                pass
    xf = data.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axis, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    out = out * gamma.reshape(bshape).astype(jnp.float32) \
        + beta.reshape(bshape).astype(jnp.float32)
    return out.astype(data.dtype)


@register("InstanceNorm", args=("data", "gamma", "beta"))
def _instance_norm(data, gamma, beta, eps=1e-3):
    """Reference: ``src/operator/instance_norm.cc``."""
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("GroupNorm", args=("data", "gamma", "beta"))
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    """Reference: ``contrib/group_norm (?v1.6)``; NCHW layout."""
    n, c = data.shape[:2]
    x = data.reshape((n, num_groups, c // num_groups) + data.shape[2:])
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


# ----------------------------------------------------------------------
# Activations / softmax
# ----------------------------------------------------------------------

@register("Activation", args=("data",))
def _activation(data, act_type="relu"):
    """Reference: ``src/operator/nn/activation.cc``."""
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    if act_type == "log_sigmoid":
        return jax.nn.log_sigmoid(data)
    if act_type == "mish":
        return data * jnp.tanh(jax.nn.softplus(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "gelu_tanh":
        return jax.nn.gelu(data, approximate=True)
    raise MXNetError("Activation: bad act_type %r" % act_type)


@register("LeakyReLU", args=("data",))
def _leaky_relu(data, act_type="leaky", slope=0.25, lower_bound=0.125, upper_bound=0.334):
    """Reference: ``src/operator/leaky_relu.cc`` (prelu is ``_prelu``)."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        return jax.nn.selu(data)
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    raise MXNetError("LeakyReLU: bad act_type %r" % act_type)


@register("_prelu", args=("data", "gamma"))
def _prelu(data, gamma):
    bshape = [1] * data.ndim
    if data.ndim > 1:
        bshape[1] = -1
    else:
        bshape[0] = -1
    return jnp.where(data > 0, data, gamma.reshape(bshape) * data)


@register("softmax", args=("data",), aliases=("SoftmaxActivation",))
def _softmax(data, axis=-1, temperature=None):
    """Reference: ``src/operator/nn/softmax.cc``."""
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.softmax(data, axis=axis)


@register("log_softmax", args=("data",))
def _log_softmax(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@register("softmin", args=("data",))
def _softmin(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        multi_output, normalization):
    if multi_output:
        prob = jax.nn.softmax(data, axis=1)
    else:
        prob = jax.nn.softmax(data, axis=-1)
    return prob


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore,
                         multi_output, normalization_code):
    # flags are static (nondiff_argnums): they steer Python control flow
    # and must not be abstracted by custom_vjp tracing
    return _softmax_output_fwd(data, label, grad_scale, ignore_label,
                               use_ignore, multi_output, normalization_code)


def _softmax_output_core_fwd(data, label, grad_scale, ignore_label,
                             use_ignore, multi_output, normalization_code):
    prob = _softmax_output_fwd(data, label, grad_scale, ignore_label,
                               use_ignore, multi_output, normalization_code)
    return prob, (prob, label)


def _softmax_output_core_bwd(grad_scale, ignore_label, use_ignore,
                             multi_output, norm_code, res, g):
    prob, label = res
    # The defining property of SoftmaxOutput (reference:
    # src/operator/softmax_output.cc): backward ignores the incoming
    # cotangent and emits (prob - one_hot(label)) * grad_scale.
    axis = 1 if multi_output else -1
    nclass = prob.shape[axis]
    onehot = jax.nn.one_hot(label.astype(jnp.int32), nclass, dtype=prob.dtype)
    if multi_output:
        onehot = jnp.moveaxis(onehot, -1, 1)
    grad = (prob - onehot)
    if use_ignore:
        mask = (label != ignore_label).astype(prob.dtype)
        mask = jnp.expand_dims(mask, axis=axis)
        grad = grad * mask
    if norm_code == 1:  # batch
        grad = grad / prob.shape[0]
    elif norm_code == 2:  # valid
        if use_ignore:
            valid = jnp.maximum(jnp.sum(label != ignore_label), 1)
        else:
            valid = label.size
        grad = grad / valid
    return (grad * grad_scale, jnp.zeros_like(label))


_softmax_output_core.defvjp(_softmax_output_core_fwd, _softmax_output_core_bwd)


@register("SoftmaxOutput", args=("data", "label"))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    use_ignore=False, multi_output=False, normalization="null"):
    """Softmax with built-in cross-entropy gradient (reference:
    ``src/operator/softmax_output.cc``): forward = softmax(data); backward
    writes ``(p - onehot(label)) * grad_scale`` regardless of head grad.
    """
    norm_code = {"null": 0, "batch": 1, "valid": 2}[normalization]
    return _softmax_output_core(data, label, grad_scale, ignore_label,
                                use_ignore, multi_output, norm_code)


@register("softmax_cross_entropy", args=("data", "label"))
def _softmax_cross_entropy(data, label):
    """Reference: ``src/operator/loss_binary_op.cc``; summed CE over batch."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


@register("smooth_l1", args=("data",))
def _smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@register("LinearRegressionOutput", args=("data", "label"))
def _linear_regression_output(data, label, grad_scale=1.0):
    return _regression_core(data, label, grad_scale, 0)


@register("MAERegressionOutput", args=("data", "label"))
def _mae_regression_output(data, label, grad_scale=1.0):
    return _regression_core(data, label, grad_scale, 1)


@register("LogisticRegressionOutput", args=("data", "label"))
def _logistic_regression_output(data, label, grad_scale=1.0):
    return _regression_core(data, label, grad_scale, 2)


@jax.custom_vjp
def _regression_core(data, label, grad_scale, kind):
    if kind == 2:
        return jax.nn.sigmoid(data)
    return data


def _regression_core_fwd(data, label, grad_scale, kind):
    out = _regression_core(data, label, grad_scale, kind)
    return out, (out, label, grad_scale, kind)


def _regression_core_bwd(res, g):
    out, label, grad_scale, kind = res
    label = label.reshape(out.shape)
    if kind == 1:
        grad = jnp.sign(out - label)
    else:
        grad = out - label
    n = out.shape[0] if out.ndim else 1
    grad = grad * grad_scale / (out.size // max(n, 1))
    return (grad, jnp.zeros_like(label), None, None)


_regression_core.defvjp(_regression_core_fwd, _regression_core_bwd)


@register("MakeLoss", args=("data",), aliases=("make_loss",))
def _make_loss(data, grad_scale=1.0, normalization="null"):
    """Reference: ``src/operator/make_loss.cc``."""
    return _make_loss_core(data, grad_scale)


@jax.custom_vjp
def _make_loss_core(data, grad_scale):
    return data


def _make_loss_core_fwd(data, grad_scale):
    return data, (data.shape, data.dtype, grad_scale)


def _make_loss_core_bwd(res, g):
    shape, dtype, grad_scale = res
    return (jnp.full(shape, grad_scale, dtype=dtype), None)


_make_loss_core.defvjp(_make_loss_core_fwd, _make_loss_core_bwd)


# ----------------------------------------------------------------------
# Embedding / dropout
# ----------------------------------------------------------------------

@register("Embedding", args=("data", "weight"))
def _embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
               sparse_grad=False):
    """Reference: ``indexing_op.cc :: Embedding``; gather on MXU-adjacent
    VMEM; gradient is a scatter-add (XLA emits it from the vjp)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("Dropout", args=("data",), stateful_rng=True)
def _dropout(key, data, p=0.5, mode="training", axes=(), cudnn_off=False,
             training=False):
    """Reference: ``src/operator/nn/dropout.cc``.

    ``key`` is injected by the dispatcher (stateful_rng).  ``mode='always'``
    applies dropout in inference too.
    """
    if p <= 0 or (not training and mode != "always"):
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(data.shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(data.dtype)
    return data * mask / keep


# ----------------------------------------------------------------------
# Fused RNN (reference: src/operator/rnn.cc + cudnn_rnn-inl.h).
# ----------------------------------------------------------------------

def _gates_for(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}[mode]


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional):
    """Total flat parameter count, matching the layout of ``_rnn_unpack``."""
    g = _gates_for(mode)
    dirs = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        per_dir = g * state_size * in_sz + g * state_size * state_size \
            + 2 * g * state_size
        total += per_dir * dirs
    return total


def _rnn_unpack(params, mode, input_size, state_size, num_layers, bidirectional):
    """Slice the flat parameter vector into per-layer weight/bias arrays.

    Layout (documented contract of this framework, analogous to the cuDNN
    packed layout the reference uses): for each layer, for each direction:
    W_ih (G*H, in), W_hh (G*H, H), b_ih (G*H), b_hh (G*H).  LSTM gate order
    i, f, g, o; GRU gate order r, z, n.
    """
    g = _gates_for(mode)
    dirs = 2 if bidirectional else 1
    layers = []
    off = 0

    def take(n, shape):
        nonlocal off
        out = lax.dynamic_slice_in_dim(params, off, n).reshape(shape)
        off += n
        return out

    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        per_dir = []
        for _ in range(dirs):
            w_ih = take(g * state_size * in_sz, (g * state_size, in_sz))
            w_hh = take(g * state_size * state_size, (g * state_size, state_size))
            b_ih = take(g * state_size, (g * state_size,))
            b_hh = take(g * state_size, (g * state_size,))
            per_dir.append((w_ih, w_hh, b_ih, b_hh))
        layers.append(per_dir)
    return layers


def _rnn_cell_step(mode, x, h, c, w_ih, w_hh, b_ih, b_hh, H):
    gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    if mode == "lstm":
        i, f, gg, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        gg = jnp.tanh(gg)
        c_new = f * c + i * gg
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    if mode == "gru":
        xg = x @ w_ih.T + b_ih
        hg = h @ w_hh.T + b_hh
        xr, xz, xn = jnp.split(xg, 3, axis=-1)
        hr, hz, hn = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, c
    act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh
    h_new = act(gates)
    return h_new, c


def _run_rnn_layer(mode, x, h0, c0, wts, reverse, H):
    """Scan one direction of one layer over time. x: (T, N, in)."""
    w_ih, w_hh, b_ih, b_hh = wts
    xs = jnp.flip(x, 0) if reverse else x

    def step(carry, xt):
        h, c = carry
        h2, c2 = _rnn_cell_step(mode, xt, h, c, w_ih, w_hh, b_ih, b_hh, H)
        return (h2, c2), h2

    (hT, cT), ys = lax.scan(step, (h0, c0), xs)
    if reverse:
        ys = jnp.flip(ys, 0)
    return ys, hT, cT


@register("RNN", args=("data", "parameters", "state", "state_cell"),
          num_diff_outputs=None, stateful_rng=True)
def _rnn(key, data, parameters, state, state_cell, state_size=0, num_layers=1,
         mode="lstm", bidirectional=False, p=0.0, state_outputs=True,
         training=False):
    """Fused multi-layer RNN (reference: ``src/operator/rnn.cc``; cuDNN path
    ``cudnn_rnn-inl.h``).  TPU-native: `lax.scan` over time per layer --
    XLA keeps the per-step matmuls on the MXU and pipelines layers.

    data: (T, N, input) time-major, as the reference.  state/state_cell:
    (num_layers*dirs, N, H).  Returns (out, hy[, cy]) -- for lstm, 3
    outputs; otherwise 2.
    """
    T, N, input_size = data.shape
    H = state_size
    dirs = 2 if bidirectional else 1
    layers = _rnn_unpack(parameters, mode, input_size, H, num_layers, bidirectional)
    x = data
    hys, cys = [], []
    for li, per_dir in enumerate(layers):
        outs = []
        for d in range(dirs):
            h0 = state[li * dirs + d]
            c0 = state_cell[li * dirs + d] if mode == "lstm" else jnp.zeros_like(h0)
            ys, hT, cT = _run_rnn_layer(mode, x, h0, c0, per_dir[d], d == 1, H)
            outs.append(ys)
            hys.append(hT)
            cys.append(cT)
        x = jnp.concatenate(outs, axis=-1) if dirs > 1 else outs[0]
        if p > 0 and training and li < len(layers) - 1:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1 - p, x.shape).astype(x.dtype)
            x = x * mask / (1 - p)
    hy = jnp.stack(hys, axis=0)
    if mode == "lstm":
        cy = jnp.stack(cys, axis=0)
        return x, hy, cy
    return x, hy
