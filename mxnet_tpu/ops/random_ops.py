"""Random sampling operators.

TPU-native re-design of ``src/operator/random/sample_op.cc`` and
``multisample_op.cc``.  The reference draws from per-device ResourceManager
RNG states (``src/resource.cc``); here every sampler is ``stateful_rng``:
the dispatcher injects a fresh ``jax.random`` subkey split from the global
stream (``mxnet_tpu/random.py``), keeping eager calls nondeterministic-free
and jit traces reproducible (the key becomes an explicit input).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_random_uniform", args=(), stateful_rng=True, aliases=("random_uniform",))
def _random_uniform(key, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(key, shape, jnp.dtype(dtype), low, high)


@register("_random_normal", args=(), stateful_rng=True, aliases=("random_normal", "normal"))
def _random_normal(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(key, shape, jnp.dtype(dtype))


@register("_random_gamma", args=(), stateful_rng=True)
def _random_gamma(key, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(key, alpha, shape, jnp.dtype(dtype))


@register("_random_exponential", args=(), stateful_rng=True)
def _random_exponential(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(key, shape, jnp.dtype(dtype)) / lam


@register("_random_poisson", args=(), stateful_rng=True)
def _random_poisson(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(key, lam, shape).astype(jnp.dtype(dtype))


@register("_random_negative_binomial", args=(), stateful_rng=True)
def _random_negative_binomial(key, k=1, p=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(key)
    g = jax.random.gamma(k1, k, shape) * (1 - p) / p
    return jax.random.poisson(k2, g, shape).astype(jnp.dtype(dtype))


@register("_random_randint", args=(), stateful_rng=True)
def _random_randint(key, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(key, shape, low, high, jnp.dtype(dtype))


@register("_sample_multinomial", args=("data",), stateful_rng=True,
          aliases=("sample_multinomial",))
def _sample_multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    """Categorical sampling from probabilities (reference:
    ``sample_multinomial_op.cc``); data: (..., k) probabilities.  With
    ``get_prob=True`` also returns per-sample log-probabilities (the
    REINFORCE pattern upstream documents)."""
    logits = jnp.log(jnp.maximum(data, 1e-37))
    n = shape if isinstance(shape, int) else (int(jnp.prod(jnp.array(shape))) if shape else 1)
    sample_shape = (n,) if shape else ()
    s = jax.random.categorical(key, logits, axis=-1,
                               shape=sample_shape + data.shape[:-1])
    if shape:
        s = jnp.moveaxis(s, 0, -1)
    s = s.astype(jnp.dtype(dtype))
    if get_prob:
        logp = jnp.log(jnp.maximum(data, 1e-37)) - jnp.log(
            jnp.sum(data, axis=-1, keepdims=True))
        picked = jnp.take_along_axis(
            logp, s.astype(jnp.int32).reshape(data.shape[:-1] + (-1,)),
            axis=-1).reshape(s.shape)
        return s, picked
    return s


@register("_shuffle", args=("data",), stateful_rng=True, aliases=("shuffle",))
def _shuffle(key, data):
    return jax.random.permutation(key, data, axis=0)


@register("_sample_unique_zipfian", args=(), stateful_rng=True)
def _sample_unique_zipfian(key, range_max=1, shape=()):
    u = jax.random.uniform(key, shape)
    out = (jnp.exp(u * jnp.log(range_max + 1.0)) - 1.0).astype(jnp.int32)
    return jnp.clip(out, 0, range_max - 1)


def _like(name, base):
    @register(name, args=("data",), stateful_rng=True)
    def _op(key, data, low=0.0, high=1.0, loc=0.0, scale=1.0):
        if base == "uniform":
            return jax.random.uniform(key, data.shape, data.dtype, low, high)
        return loc + scale * jax.random.normal(key, data.shape, data.dtype)
    return _op


_like("_random_uniform_like", "uniform")
_like("_random_normal_like", "normal")
