"""Transformer / BERT attention operators.

TPU-native re-design of the reference's fused BERT kernels
(``src/operator/contrib/transformer.cc :: interleaved_matmul_selfatt_qk,
interleaved_matmul_selfatt_valatt, interleaved_matmul_encdec_qk,
interleaved_matmul_encdec_valatt``).  The interleaved layout -- one
projection tensor (seq, batch, heads * 3 * head_dim) with each head's
q/k/v contiguous -- is kept for API parity; the score scaling
1/sqrt(head_dim) is applied inside the qk op.

``flash_attention`` is the TPU answer to these kernels: a Pallas
blockwise online-softmax kernel (``ops/pallas/flash_attention.py``) that
never materializes the (seq, seq) score matrix in HBM.  Backward is
recompute-based (standard attention math, XLA-fused), trading FLOPs for
memory exactly like ``jax.checkpoint``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .registry import register


# ----------------------------------------------------------------------
# Interleaved-projection ops (reference API parity)
# ----------------------------------------------------------------------

def _split_selfatt(qkv, heads):
    # (seq, batch, heads*3*hd) -> q/k/v each (batch*heads, seq, hd)
    seq, batch, emb3 = qkv.shape
    hd = emb3 // (3 * heads)
    x = qkv.reshape(seq, batch, heads, 3, hd)
    # (batch, heads, seq, hd) order for batched matmul
    q = x[:, :, :, 0].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    k = x[:, :, :, 1].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    v = x[:, :, :, 2].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    return q, k, v, hd


@register("interleaved_matmul_selfatt_qk", args=("queries_keys_values",))
def _interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """Scores = Q·K^T / sqrt(head_dim) from an interleaved qkv projection
    (reference: ``transformer.cc :: interleaved_matmul_selfatt_qk``).
    Input (seq, batch, heads*3*hd); output (batch*heads, seq, seq)."""
    q, k, _, hd = _split_selfatt(queries_keys_values, heads)
    scale = 1.0 / math.sqrt(hd)
    return jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,)))) * scale


@register("interleaved_matmul_selfatt_valatt",
          args=("queries_keys_values", "attention"))
def _interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                       heads=1):
    """Out = softmax-scores · V, back to (seq, batch, embed) (reference:
    ``interleaved_matmul_selfatt_valatt``)."""
    seq, batch, emb3 = queries_keys_values.shape
    _, _, v, hd = _split_selfatt(queries_keys_values, heads)
    out = jax.lax.dot_general(
        attention, v, (((2,), (1,)), ((0,), (0,))))  # (b*h, seq, hd)
    out = out.reshape(batch, heads, seq, hd).transpose(2, 0, 1, 3)
    return out.reshape(seq, batch, heads * hd)


def _split_encdec(kv, heads):
    seq, batch, emb2 = kv.shape
    hd = emb2 // (2 * heads)
    x = kv.reshape(seq, batch, heads, 2, hd)
    k = x[:, :, :, 0].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    v = x[:, :, :, 1].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    return k, v, hd


@register("interleaved_matmul_encdec_qk", args=("queries", "keys_values"))
def _interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """Cross-attention scores (reference: ``interleaved_matmul_encdec_qk``).
    queries (qlen, batch, embed); keys_values (kvlen, batch, 2*embed
    interleaved); output (batch*heads, qlen, kvlen)."""
    qlen, batch, emb = queries.shape
    hd = emb // heads
    q = queries.reshape(qlen, batch, heads, hd) \
        .transpose(1, 2, 0, 3).reshape(batch * heads, qlen, hd)
    k, _, _ = _split_encdec(keys_values, heads)
    scale = 1.0 / math.sqrt(hd)
    return jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,)))) * scale


@register("interleaved_matmul_encdec_valatt",
          args=("keys_values", "attention"))
def _interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    """Reference: ``interleaved_matmul_encdec_valatt``."""
    kvlen, batch, emb2 = keys_values.shape
    _, v, hd = _split_encdec(keys_values, heads)
    qlen = attention.shape[1]
    out = jax.lax.dot_general(
        attention, v, (((2,), (1,)), ((0,), (0,))))
    out = out.reshape(batch, heads, qlen, hd).transpose(2, 0, 1, 3)
    return out.reshape(qlen, batch, heads * hd)


# ----------------------------------------------------------------------
# Flash attention
# ----------------------------------------------------------------------

def _attention_reference(q, k, v, causal, scale):
    """Plain XLA attention (fallback + backward math)."""
    s = jax.lax.dot_general(
        q.astype(jnp.float32), k.astype(jnp.float32),
        (((2,), (2,)), ((0,), (0,)))) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        s = jnp.where(rows >= cols, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jax.lax.dot_general(
        p, v.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))))
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, use_pallas):
    if use_pallas:
        from .pallas.flash_attention import flash_attention_fwd_pallas
        return flash_attention_fwd_pallas(q, k, v, causal=causal,
                                          scale=scale, block_q=block_q,
                                          block_k=block_k)
    return _attention_reference(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, use_pallas):
    return _flash(q, k, v, causal, scale, block_q, block_k, use_pallas), \
        (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, use_pallas, res, dout):
    # Recompute-based backward: rebuild p in fp32, standard attention
    # gradients.  XLA fuses this well; memory O(seq^2) only transiently
    # per fusion tile.
    q, k, v = res
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
    s = jax.lax.dot_general(qf, kf, (((2,), (2,)), ((0,), (0,)))) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        s = jnp.where(rows >= cols, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    do = dout.astype(jnp.float32)
    dv = jax.lax.dot_general(p, do, (((1,), (1,)), ((0,), (0,))))
    dp = jax.lax.dot_general(do, vf, (((2,), (2,)), ((0,), (0,))))
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jax.lax.dot_general(ds, kf, (((2,), (1,)), ((0,), (0,)))) * scale
    dk = jax.lax.dot_general(ds, qf, (((1,), (1,)), ((0,), (0,)))) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


@register("flash_attention", args=("q", "k", "v"))
def _flash_attention_op(q, k, v, causal=False, scale=-1.0, use_pallas=False,
                        block_q=256, block_k=256):
    """Fused scaled-dot-product attention over (batch*heads, seq,
    head_dim) tensors.  ``use_pallas=True`` selects the Pallas TPU kernel
    (``ops/pallas/flash_attention.py``); the default runs the XLA
    reference path (correct everywhere, fused by the compiler).
    ``scale < 0`` means 1/sqrt(head_dim)."""
    if scale is None or scale < 0:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, bool(causal), float(scale), int(block_q),
                  int(block_k), bool(use_pallas))
