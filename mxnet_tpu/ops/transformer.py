"""Transformer / BERT attention operators.

TPU-native re-design of the reference's fused BERT kernels
(``src/operator/contrib/transformer.cc :: interleaved_matmul_selfatt_qk,
interleaved_matmul_selfatt_valatt, interleaved_matmul_encdec_qk,
interleaved_matmul_encdec_valatt``).  The interleaved layout -- one
projection tensor (seq, batch, heads * 3 * head_dim) with each head's
q/k/v contiguous -- is kept for API parity; the score scaling
1/sqrt(head_dim) is applied inside the qk op.

``flash_attention`` is the TPU answer to these kernels: a Pallas
blockwise online-softmax kernel (``ops/pallas/flash_attention.py``) that
never materializes the (seq, seq) score matrix in HBM.  Backward is
recompute-based (standard attention math, XLA-fused), trading FLOPs for
memory exactly like ``jax.checkpoint``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .registry import register


# ----------------------------------------------------------------------
# Interleaved-projection ops (reference API parity)
# ----------------------------------------------------------------------

def _split_selfatt(qkv, heads):
    # (seq, batch, heads*3*hd) -> q/k/v each (batch*heads, seq, hd)
    seq, batch, emb3 = qkv.shape
    hd = emb3 // (3 * heads)
    x = qkv.reshape(seq, batch, heads, 3, hd)
    # (batch, heads, seq, hd) order for batched matmul
    q = x[:, :, :, 0].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    k = x[:, :, :, 1].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    v = x[:, :, :, 2].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    return q, k, v, hd


@register("interleaved_matmul_selfatt_qk", args=("queries_keys_values",))
def _interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """Scores = Q·K^T / sqrt(head_dim) from an interleaved qkv projection
    (reference: ``transformer.cc :: interleaved_matmul_selfatt_qk``).
    Input (seq, batch, heads*3*hd); output (batch*heads, seq, seq)."""
    q, k, _, hd = _split_selfatt(queries_keys_values, heads)
    scale = 1.0 / math.sqrt(hd)
    return jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,)))) * scale


@register("interleaved_matmul_selfatt_valatt",
          args=("queries_keys_values", "attention"))
def _interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                       heads=1):
    """Out = softmax-scores · V, back to (seq, batch, embed) (reference:
    ``interleaved_matmul_selfatt_valatt``)."""
    seq, batch, emb3 = queries_keys_values.shape
    _, _, v, hd = _split_selfatt(queries_keys_values, heads)
    out = jax.lax.dot_general(
        attention, v, (((2,), (1,)), ((0,), (0,))))  # (b*h, seq, hd)
    out = out.reshape(batch, heads, seq, hd).transpose(2, 0, 1, 3)
    return out.reshape(seq, batch, heads * hd)


def _split_encdec(kv, heads):
    seq, batch, emb2 = kv.shape
    hd = emb2 // (2 * heads)
    x = kv.reshape(seq, batch, heads, 2, hd)
    k = x[:, :, :, 0].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    v = x[:, :, :, 1].transpose(1, 2, 0, 3).reshape(batch * heads, seq, hd)
    return k, v, hd


@register("interleaved_matmul_encdec_qk", args=("queries", "keys_values"))
def _interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """Cross-attention scores (reference: ``interleaved_matmul_encdec_qk``).
    queries (qlen, batch, embed); keys_values (kvlen, batch, 2*embed
    interleaved); output (batch*heads, qlen, kvlen)."""
    qlen, batch, emb = queries.shape
    hd = emb // heads
    q = queries.reshape(qlen, batch, heads, hd) \
        .transpose(1, 2, 0, 3).reshape(batch * heads, qlen, hd)
    k, _, _ = _split_encdec(keys_values, heads)
    scale = 1.0 / math.sqrt(hd)
    return jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,)))) * scale


@register("interleaved_matmul_encdec_valatt",
          args=("keys_values", "attention"))
def _interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    """Reference: ``interleaved_matmul_encdec_valatt``."""
    kvlen, batch, emb2 = keys_values.shape
    _, v, hd = _split_encdec(keys_values, heads)
    qlen = attention.shape[1]
    out = jax.lax.dot_general(
        attention, v, (((2,), (1,)), ((0,), (0,))))
    out = out.reshape(batch, heads, qlen, hd).transpose(2, 0, 1, 3)
    return out.reshape(qlen, batch, heads * hd)


# ----------------------------------------------------------------------
# Flash attention
# ----------------------------------------------------------------------

def _attention_reference(q, k, v, causal, scale):
    """Plain XLA attention (fallback + backward math).  Matmuls run in
    the input dtype with fp32 accumulation -- the MXU-native mode (a
    bf16 x bf16 product is exact in fp32, so this matches an fp32
    upcast to accumulation-order) -- softmax in fp32."""
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        s = jnp.where(rows >= cols, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _xla_attention_bwd(q, k, v, dout, causal, scale, mask=None):
    # Recompute-based backward (XLA path): rebuild p in fp32, standard
    # attention gradients.
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
    s = jax.lax.dot_general(qf, kf, (((2,), (2,)), ((0,), (0,)))) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        s = jnp.where(rows >= cols, s, -1e30)
    if mask is not None:
        s = jnp.where(mask > 0, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    do = dout.astype(jnp.float32)
    dv = jax.lax.dot_general(p, do, (((1,), (1,)), ((0,), (0,))))
    dp = jax.lax.dot_general(do, vf, (((2,), (2,)), ((0,), (0,))))
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jax.lax.dot_general(ds, kf, (((2,), (1,)), ((0,), (0,)))) * scale
    dk = jax.lax.dot_general(ds, qf, (((1,), (1,)), ((0,), (0,)))) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, use_pallas,
           interpret):
    if use_pallas:
        from .pallas.flash_attention import flash_attention_fwd_pallas
        out, _lse = flash_attention_fwd_pallas(
            q, k, v, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, interpret=interpret)
        return out
    return _attention_reference(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, use_pallas,
               interpret):
    if use_pallas:
        from .pallas.flash_attention import flash_attention_fwd_pallas
        out, lse = flash_attention_fwd_pallas(
            q, k, v, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, interpret=interpret)
        return out, (q, k, v, out, lse)
    return _flash(q, k, v, causal, scale, block_q, block_k, use_pallas,
                  interpret), (q, k, v, None, None)


def _flash_bwd(causal, scale, block_q, block_k, use_pallas, interpret,
               res, dout):
    q, k, v, out, lse = res
    if use_pallas and lse is not None:
        # blockwise Pallas backward: O(seq*d) memory, replays score
        # blocks from the saved logsumexp
        from .pallas.flash_attention import flash_attention_bwd_pallas
        delta = jnp.sum(dout.astype(jnp.float32)
                        * out.astype(jnp.float32), axis=-1)
        return flash_attention_bwd_pallas(
            q, k, v, lse, dout, delta, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k, interpret=interpret)
    return _xla_attention_bwd(q, k, v, dout, causal, scale)


_flash.defvjp(_flash_fwd, _flash_bwd)


# masked variant: the padding mask (batch, seq_q, seq_k) rides into the
# kernels; heads is static so programs can map bh -> batch
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_masked(q, k, v, maskf, scale, block_q, block_k, use_pallas,
                  heads, interpret):
    if use_pallas:
        from .pallas.flash_attention import flash_attention_fwd_pallas
        out, _lse = flash_attention_fwd_pallas(
            q, k, v, maskf, causal=False, scale=scale, block_q=block_q,
            block_k=block_k, heads=heads, interpret=interpret)
        return out
    m = jnp.repeat(maskf, heads, axis=0)
    return _attention_reference_masked(q, k, v, m, scale)


def _attention_reference_masked(q, k, v, mask_bh, scale):
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask_bh > 0, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _flash_masked_fwd(q, k, v, maskf, scale, block_q, block_k, use_pallas,
                      heads, interpret):
    if use_pallas:
        from .pallas.flash_attention import flash_attention_fwd_pallas
        out, lse = flash_attention_fwd_pallas(
            q, k, v, maskf, causal=False, scale=scale, block_q=block_q,
            block_k=block_k, heads=heads, interpret=interpret)
        return out, (q, k, v, maskf, out, lse)
    out = _flash_masked(q, k, v, maskf, scale, block_q, block_k,
                        use_pallas, heads, interpret)
    return out, (q, k, v, maskf, None, None)


def _flash_masked_bwd(scale, block_q, block_k, use_pallas, heads,
                      interpret, res, dout):
    q, k, v, maskf, out, lse = res
    if use_pallas and lse is not None:
        from .pallas.flash_attention import flash_attention_bwd_pallas
        delta = jnp.sum(dout.astype(jnp.float32)
                        * out.astype(jnp.float32), axis=-1)
        dq, dk, dv = flash_attention_bwd_pallas(
            q, k, v, lse, dout, delta, maskf, causal=False, scale=scale,
            block_q=block_q, block_k=block_k, heads=heads,
            interpret=interpret)
    else:
        m = jnp.repeat(maskf, heads, axis=0)
        dq, dk, dv = _xla_attention_bwd(q, k, v, dout, False, scale,
                                        mask=m)
    return dq, dk, dv, jnp.zeros_like(maskf)


_flash_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


def _kernel_choice(seq, block_q, block_k, use_pallas):
    """THE selection point (docs/kernels.md): one registry consult
    replaces the five ``use_pallas`` branches that used to be scattered
    through this file.  Auto mode carries the measured v5e crossover
    (seq >= 256 -- see ``kernels/flash_attention.py`` for the per-seq
    numbers) and picks the Pallas kernels on TPU only; forced mode runs
    them in interpret mode on CPU so tests exercise the kernel bodies;
    availability and seq/block divisibility are checked once here."""
    from ..kernels import choose
    return choose("flash_attention", force=use_pallas, seq=seq,
                  block_q=block_q, block_k=block_k)


@register("flash_attention", args=("q", "k", "v"))
def _flash_attention_op(q, k, v, causal=False, scale=-1.0, use_pallas=None,
                        block_q=256, block_k=256):
    """Fused scaled-dot-product attention over (batch*heads, seq,
    head_dim) tensors.  ``use_pallas``: True = Pallas kernels (forward
    AND blockwise backward, O(seq*d) memory), False = XLA reference
    path (plain softmax attention, autodiffed by XLA -- the fastest
    short-sequence path), None (default) = the kernel registry's
    policy (``kernels.choose('flash_attention')``): Pallas above the
    measured crossover on TPU, the plain XLA path otherwise -- with no
    custom_vjp wrapper on the fallback, so XLA saves the softmax from
    the forward instead of recomputing it in the backward.
    ``scale < 0`` means 1/sqrt(head_dim)."""
    if scale is None or scale < 0:
        scale = 1.0 / math.sqrt(q.shape[-1])
    causal, scale = bool(causal), float(scale)
    block_q, block_k = int(block_q), int(block_k)
    ch = _kernel_choice(q.shape[1], block_q, block_k, use_pallas)
    if ch.use_pallas:
        return _flash(q, k, v, causal, scale, block_q, block_k, True,
                      ch.interpret)
    return _attention_reference(q, k, v, causal, scale)


@register("flash_attention_masked", args=("q", "k", "v", "mask"))
def _flash_attention_masked_op(q, k, v, mask, scale=-1.0, use_pallas=None,
                               heads=1, block_q=256, block_k=256):
    """Masked flash attention: ``mask`` is (batch, seq_q, seq_k) with
    nonzero = attend, shared across the ``heads`` heads folded into
    q/k/v's leading dim.  Same kernel selection rules as
    ``flash_attention`` (one registry consult)."""
    if scale is None or scale < 0:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scale = float(scale)
    block_q, block_k = int(block_q), int(block_k)
    heads = int(heads)
    maskf = mask.astype(jnp.float32)
    ch = _kernel_choice(q.shape[1], block_q, block_k, use_pallas)
    if ch.use_pallas:
        return _flash_masked(q, k, v, maskf, scale, block_q, block_k,
                             True, heads, ch.interpret)
    return _attention_reference_masked(
        q, k, v, jnp.repeat(maskf, heads, axis=0), scale)
