"""Fused LayerNorm forward kernel (Pallas/TPU).

One VMEM pass per row-block: load (block_rows, dim), compute mean/var
in fp32, normalize, scale/shift, write -- where the unfused graph reads
x three times from HBM (mean pass, var pass, normalize pass) before XLA
fusion, this guarantees the single-pass schedule and keeps the
activation bf16 in HBM with fp32 statistics in registers.  Reference
analog: the fused ``LayerNorm`` CUDA kernel in
``src/operator/nn/layer_norm.cu``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)              # (block_rows, dim)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = xc * inv * g_ref[...].astype(jnp.float32) + \
        b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "block_rows",
                                             "interpret"))
def layernorm_fwd_pallas(x, gamma, beta, eps=1e-5, block_rows=128,
                         interpret=False):
    """LayerNorm over the last dim of a 2-D (rows, dim) input."""
    rows, dim = x.shape
    if rows == 0:
        return x
    block_rows = min(block_rows, rows)
    while rows % block_rows != 0:
        block_rows -= 1          # largest divisor <= requested block
    grid = (rows // block_rows,)
    kernel = functools.partial(_ln_kernel, eps=eps)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, dim), lambda i: (i, 0)),
            pl.BlockSpec((dim,), lambda i: (0,)),
            pl.BlockSpec((dim,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, dim), lambda i: (i, 0)),
        interpret=interpret,
    )(x, gamma, beta)
