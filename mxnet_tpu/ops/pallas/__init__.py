"""Hand-written Pallas TPU kernel bodies (flash attention, layernorm).

Selection/fallback policy lives in ``mxnet_tpu.kernels`` (the kernel
registry, docs/kernels.md); these modules hold only the kernels.
"""
from .flash_attention import (flash_attention_bwd_pallas,
                              flash_attention_fwd_pallas)

__all__ = ["flash_attention_fwd_pallas", "flash_attention_bwd_pallas"]
