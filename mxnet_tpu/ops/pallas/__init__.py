"""Hand-written Pallas TPU kernels for hot ops (flash attention)."""
from .flash_attention import flash_attention_fwd_pallas

__all__ = ["flash_attention_fwd_pallas"]
