"""Decode-step paged attention (Pallas/TPU): one query token per
sequence attends over block-gathered K/V from the serving tier's
:class:`~mxnet_tpu.serving.decode.kvcache.PagedKVCache`.

The prefill kernels (``flash_attention.py``) stream CONTIGUOUS K/V; at
decode time a sequence's K/V is scattered over cache blocks named by
its block table, so the kernel walks the table -- online softmax across
blocks, exactly the flash discipline, but the block index is data (the
table row), not the grid position.  The XLA reference gathers the
table's blocks with one ``take`` and runs a masked softmax -- it is the
CPU fallback and the numerics oracle the registry's interpret-mode
contract is tested against.

Layout: q ``(slots, heads, head_dim)``; per-layer cache slabs
``(num_blocks, block_size, heads, head_dim)``; ``block_tables``
``(slots, max_blocks)`` int32; ``context_lens`` ``(slots, 1)`` int32
(tokens 0..ctx-1 are live).  fp32 accumulation regardless of cache
dtype.  The whole slab pair is presented to each program (VMEM-bounded
on real hardware -- sized for the serving tier's preallocated caches;
interpret mode has no such bound).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30

try:  # pallas import kept lazy-safe: CPU-only builds fall back to XLA
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


# ----------------------------------------------------------------------
# XLA reference / fallback
# ----------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("scale",))
def paged_attention_reference(q, k_cache, v_cache, block_tables,
                              context_lens, scale=1.0):
    """Gather-then-softmax reference: ``take`` the table's blocks into
    a contiguous ``(slots, max_blocks*block_size, heads, d)`` view and
    mask positions past each slot's context length."""
    s_, h, d = q.shape
    nb, bs, _, _ = k_cache.shape
    mb = block_tables.shape[1]
    k = jnp.take(k_cache, block_tables, axis=0)        # (s, mb, bs, h, d)
    v = jnp.take(v_cache, block_tables, axis=0)
    k = k.reshape(s_, mb * bs, h, d).astype(jnp.float32)
    v = v.reshape(s_, mb * bs, h, d).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    scores = jnp.einsum("shd,sthd->sht", qf, k) * scale
    pos = jnp.arange(mb * bs, dtype=jnp.int32)
    live = pos[None, None, :] < context_lens.reshape(s_, 1, 1)
    scores = jnp.where(live, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("sht,sthd->shd", p / l, v)
    return out.astype(q.dtype)


# ----------------------------------------------------------------------
# Pallas kernel: grid over slots, online softmax across table blocks
# ----------------------------------------------------------------------

def _decode_kernel(q_ref, k_ref, v_ref, bt_ref, ctx_ref, o_ref, *,
                   block_size, scale, max_blocks):
    q = q_ref[0].astype(jnp.float32)              # (heads, d)
    heads, d = q.shape
    ctx = ctx_ref[0, 0]
    num_blocks = jax.lax.div(ctx + block_size - 1, block_size)

    def body(j, carry):
        m, l, acc = carry
        blk = bt_ref[0, j]
        k = k_ref[blk].astype(jnp.float32)        # (bs, heads, d)
        v = v_ref[blk].astype(jnp.float32)
        # (heads, 1, d) x (heads, bs, d) -> (heads, 1, bs): one query
        # row per head against the block's keys
        s = jax.lax.dot_general(
            q[:, None, :], k.transpose(1, 0, 2),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)[:, 0, :] * scale
        tpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (heads, block_size), 1)
        s = jnp.where(tpos < ctx, s, NEG_INF)     # (heads, bs)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        # (heads, 1, bs) x (heads, bs, d) -> (heads, d)
        pv = jax.lax.dot_general(
            p[:, None, :], v.transpose(1, 0, 2),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)[:, 0, :]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((heads, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((heads, 1), jnp.float32)
    acc0 = jnp.zeros((heads, d), jnp.float32)
    _m, l, acc = jax.lax.fori_loop(0, num_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_attention_pallas(q, k_cache, v_cache, block_tables,
                           context_lens, scale=1.0, interpret=False):
    """q (slots, heads, d); caches (nb, bs, heads, d); block_tables
    (slots, mb) int32; context_lens (slots, 1) int32 -> (slots, heads,
    d)."""
    slots, heads, d = q.shape
    nb, bs, _, _ = k_cache.shape
    mb = block_tables.shape[1]
    kernel = functools.partial(_decode_kernel, block_size=bs,
                               scale=scale, max_blocks=mb)
    cache_spec = pl.BlockSpec((nb, bs, heads, d),
                              lambda s: (0, 0, 0, 0))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(slots,),
        in_specs=[
            pl.BlockSpec((1, heads, d), lambda s: (s, 0, 0)),
            cache_spec,
            cache_spec,
            pl.BlockSpec((1, mb), lambda s: (s, 0)),
            pl.BlockSpec((1, 1), lambda s: (s, 0)),
        ],
        out_specs=pl.BlockSpec((1, heads, d), lambda s: (s, 0, 0)),
        interpret=interpret,
    )(q, k_cache, v_cache, block_tables, context_lens)
