"""Flash attention kernels (Pallas/TPU): forward AND backward, with
optional padding-mask support.

Replaces the reference's fused BERT attention kernels
(``src/operator/contrib/transformer.cc :: interleaved_matmul_selfatt_*``,
which materialize the (seq, seq) score matrix in HBM) with the blockwise
online-softmax algorithm: scores never leave VMEM, so HBM traffic is
O(seq*d) instead of O(seq^2) in BOTH directions -- the backward replays
score blocks from the forward-saved logsumexp instead of materializing
the fp32 score matrix, which is what makes long-context training
memory-feasible.

Layout: (batch*heads, seq, head_dim); optional mask (batch, seq, seq)
with 1 = attend (``heads`` static so kernels can map bh -> batch).
fp32 accumulation regardless of input dtype (MXU-native bf16 in, fp32
accumulate).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30

try:  # pallas import kept lazy-safe: CPU-only builds fall back to XLA
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------

def _fwd_kernel(*refs, block_k, causal, scale, seq_len, has_mask):
    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        mask_ref = None
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)           # (block_q, d)
    block_q = q.shape[0]
    d = q.shape[1]

    num_kv = pl.cdiv(seq_len, block_k)
    if causal:
        # only blocks at or left of the diagonal contribute
        num_kv = pl.cdiv((qi + 1) * block_q, block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if mask_ref is not None:
            mblk = mask_ref[0, :, pl.ds(j * block_k, block_k)]
            s = jnp.where(mblk > 0, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # logsumexp per row, replicated over 8 sublanes: Mosaic requires the
    # last two block dims be (8, 128)-tileable, so a (1, block_q) row
    # is stored as (8, block_q) and row 0 read back
    row = (m + jnp.log(l_safe))[:, 0]
    lse_ref[0] = jnp.broadcast_to(row[None, :], (8, row.shape[0]))


def _qmask_spec(block_q, seq, heads):
    # mask is (batch, seq, seq); program b indexes batch = bh // heads
    return pl.BlockSpec((1, block_q, seq),
                        lambda b, i: (b // heads, i, 0))


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "heads", "interpret"))
def flash_attention_fwd_pallas(q, k, v, mask=None, causal=False, scale=1.0,
                               block_q=256, block_k=256, heads=1,
                               interpret=False):
    """q,k,v: (bh, seq, d) [+ mask (b, seq, seq), 1 = attend]
    -> (out (bh, seq, d), lse (bh, seq))."""
    bh, seq, d = q.shape
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    assert seq % block_q == 0 and seq % block_k == 0, \
        "flash attention needs seq divisible by block sizes"
    grid = (bh, seq // block_q)
    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               scale=scale, seq_len=seq,
                               has_mask=mask is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(_qmask_spec(block_q, seq, heads))
        args.append(mask)
    out, lse8 = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((bh, 8, seq), jnp.float32)],
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, 8, block_q),
                                lambda b, i: (b, 0, i))],
        interpret=interpret,
    )(*args)
    return out, lse8[:, 0, :]


# ----------------------------------------------------------------------
# backward: dk/dv kernel (grid over kv blocks) + dq kernel (q blocks)
# ----------------------------------------------------------------------

def _bwd_dkv_kernel(*refs, block_q, causal, scale, seq_len, has_mask):
    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
         dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref) = refs
        mask_ref = None
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)            # (block_k, d)
    v = v_ref[0].astype(jnp.float32)
    block_k = k.shape[0]
    d = k.shape[1]

    start_q = 0
    if causal:
        # q rows strictly above the block's first kv column never attend
        start_q = (ki * block_k) // block_q

    def body(j, carry):
        dk, dv = carry
        qj = q_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        doj = do_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(j * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(j * block_q, block_q)]
        s = jax.lax.dot_general(
            qj, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if mask_ref is not None:
            mblk = mask_ref[0, pl.ds(j * block_q, block_q), :]
            s = jnp.where(mblk > 0, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])            # (bq, bk)
        dv_new = dv + jax.lax.dot_general(
            p, doj, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)   # (bk, d)
        dp = jax.lax.dot_general(
            doj, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (bq, bk)
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, qj, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)   # (bk, d)
        return dk_new, dv_new

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, pl.cdiv(seq_len, block_q), body,
                               (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, block_k, causal, scale, seq_len, has_mask):
    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
         dq_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref) = refs
        mask_ref = None
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)            # (block_q, d)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    block_q = q.shape[0]
    d = q.shape[1]

    num_kv = pl.cdiv(seq_len, block_k)
    if causal:
        num_kv = pl.cdiv((qi + 1) * block_q, block_k)

    def body(j, dq):
        kj = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vj = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if mask_ref is not None:
            mblk = mask_ref[0, :, pl.ds(j * block_k, block_k)]
            s = jnp.where(mblk > 0, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, vj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, kj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kv, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "heads", "interpret"))
def flash_attention_bwd_pallas(q, k, v, lse, dout, delta, mask=None,
                               causal=False, scale=1.0, block_q=256,
                               block_k=256, heads=1, interpret=False):
    """Blockwise flash backward -> (dq, dk, dv), O(seq*d) memory.

    ``delta`` is rowsum(dout * out) -- the softmax-jacobian correction,
    computed outside so the saved residuals are just (q, k, v, out, lse).
    """
    bh, seq, d = q.shape
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)

    # (bh, seq) row vectors carried in the (bh, 8, seq) sublane-
    # replicated layout the Mosaic tiling rules want (see fwd)
    lse8 = jnp.broadcast_to(lse[:, None, :], (bh, 8, seq))
    delta8 = jnp.broadcast_to(delta[:, None, :], (bh, 8, seq))

    seq_spec = pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0))
    vec_spec = pl.BlockSpec((1, 8, seq), lambda b, i: (b, 0, 0))

    args = [q, k, v, dout, lse8, delta8]
    dkv_specs = [seq_spec,
                 pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
                 pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
                 seq_spec, vec_spec, vec_spec]
    dq_specs = [pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                seq_spec, seq_spec,
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
                pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i))]
    if mask is not None:
        # dkv iterates q rows with kv fixed: full rows x block_k columns
        dkv_specs.append(pl.BlockSpec(
            (1, seq, block_k), lambda b, i: (b // heads, 0, i)))
        dq_specs.append(_qmask_spec(block_q, seq, heads))
        args.append(mask)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale, seq_len=seq,
                          has_mask=mask is not None),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        grid=(bh, seq // block_k),
        in_specs=dkv_specs,
        out_specs=[pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0))],
        interpret=interpret,
    )(*args)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale, seq_len=seq,
                          has_mask=mask is not None),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, seq // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(*args)
    return dq, dk, dv
