"""Flash attention forward kernel (Pallas/TPU).

Replaces the reference's fused BERT attention kernels
(``src/operator/contrib/transformer.cc :: interleaved_matmul_selfatt_*``,
which materialize the (seq, seq) score matrix in HBM) with the blockwise
online-softmax algorithm: scores never leave VMEM, so HBM traffic is
O(seq*d) instead of O(seq^2) and long sequences stop being
bandwidth-bound.

Layout: (batch*heads, seq, head_dim) -- grid over (bh, q_block); each
program streams KV blocks through VMEM with a running (max, sum, acc)
carry.  fp32 accumulation regardless of input dtype (MXU-native bf16 in,
fp32 out).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale,
                seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)           # (block_q, d)
    block_q = q.shape[0]
    d = q.shape[1]

    num_kv = pl.cdiv(seq_len, block_k)
    if causal:
        # only blocks at or left of the diagonal contribute
        num_kv = pl.cdiv((qi + 1) * block_q, block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


try:  # pallas import kept lazy-safe: CPU-only builds fall back to XLA
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention_fwd_pallas(q, k, v, causal=False, scale=1.0,
                               block_q=256, block_k=256, interpret=False):
    """q,k,v: (bh, seq, d) -> (bh, seq, d)."""
    bh, seq, d = q.shape
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    assert seq % block_q == 0 and seq % block_k == 0, \
        "flash attention needs seq divisible by block sizes"
    grid = (bh, seq // block_q)
    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               scale=scale, seq_len=seq)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)
