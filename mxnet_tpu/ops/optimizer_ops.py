"""Fused optimizer update operators.

TPU-native re-design of ``src/operator/optimizer_op.cc`` (``sgd_update``,
``sgd_mom_update``, ``mp_sgd*`` multi-precision, ``adam_update``,
``lamb_update_phase1/2``, ``ftrl_update``, ``rmsprop_update`` ...).
Functional contract: the reference mutates weight/state through the
engine's mutable vars; here each op *returns* the updated tensors and the
Python ``Optimizer``/``Trainer`` rebinds -- under jit the whole update
fuses into one XLA computation with donated buffers, which is the TPU
equivalent of the reference's single fused CUDA kernel.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _apply_wd(grad, weight, wd, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", args=("weight", "grad"))
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    return weight - lr * g


@register("sgd_mom_update", args=("weight", "grad", "mom"))
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", args=("weight", "grad", "mom"))
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("mp_sgd_update", args=("weight", "grad", "weight32"))
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    """Multi-precision SGD: fp32 master weights, low-precision model copy
    (reference: ``optimizer_op.cc :: mp_sgd_update``)."""
    g = _apply_wd(grad.astype(jnp.float32), weight32, wd, rescale_grad,
                  clip_gradient)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", args=("weight", "grad", "mom", "weight32"))
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       lazy_update=True):
    g = _apply_wd(grad.astype(jnp.float32), weight32, wd, rescale_grad,
                  clip_gradient)
    new_mom = momentum * mom - lr * g
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("adam_update", args=("weight", "grad", "mean", "var"))
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * m / (jnp.sqrt(v) + epsilon)
    return w, m, v


@register("adamw_update", args=("weight", "grad", "mean", "var"))
def _adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                  clip_gradient=-1.0):
    """Decoupled weight decay Adam (reference: ``contrib/adamw.cc``)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight)
    return w, m, v


@register("rmsprop_update", args=("weight", "grad", "n"))
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    n2 = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / (jnp.sqrt(n2) + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n2


@register("rmspropalex_update", args=("weight", "grad", "n", "g", "delta"))
def _rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    gr = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    n2 = gamma1 * n + (1 - gamma1) * jnp.square(gr)
    g2 = gamma1 * g + (1 - gamma1) * gr
    d2 = gamma2 * delta - lr * gr / jnp.sqrt(n2 - jnp.square(g2) + epsilon)
    w = weight + d2
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n2, g2, d2


@register("ftrl_update", args=("weight", "grad", "z", "n"))
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    n2 = n + jnp.square(g)
    sigma = (jnp.sqrt(n2) - jnp.sqrt(n)) / lr
    z2 = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z2) <= lamda1, jnp.zeros_like(weight),
        -(z2 - jnp.sign(z2) * lamda1) / ((beta + jnp.sqrt(n2)) / lr + wd))
    return w, z2, n2


@register("adagrad_update", args=("weight", "grad", "history"),
          aliases=("_sparse_adagrad_update",))
def _adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    h2 = history + jnp.square(g)
    w = weight - lr * (g / jnp.sqrt(h2 + epsilon) + wd * weight)
    return w, h2


@register("signsgd_update", args=("weight", "grad"))
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", args=("weight", "grad", "mom"))
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * g
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom) - lr * wd * weight
    return w, new_mom


@register("lamb_update_phase1", args=("weight", "grad", "mean", "var"))
def _lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                        epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    """LAMB phase 1 (reference: ``optimizer_op.cc :: lamb_update_phase1``):
    computes the raw update direction; phase 2 applies the trust ratio."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mh = m / (1 - beta1 ** t)
        vh = v / (1 - beta2 ** t)
    else:
        mh, vh = m, v
    gw = mh / (jnp.sqrt(vh) + epsilon) + wd * weight
    return gw, m, v


@register("lamb_update_phase2", args=("weight", "g", "r1", "r2"))
def _lamb_update_phase2(weight, g, r1, r2, lr=0.001, lower_bound=-1.0,
                        upper_bound=-1.0):
    """LAMB phase 2: trust-ratio-scaled step (reference:
    ``lamb_update_phase2``); r1=||w||, r2=||update||."""
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_or(r1 == 0, r2 == 0), 1.0, r1 / r2)
    return weight - lr * ratio * g


@register("multi_sum_sq", args=("data",), variadic=True)
def _multi_sum_sq(*data, num_arrays=1):
    """Per-array sum of squares (reference: ``multi_sum_sq.cc``; feeds
    LARS trust-ratio computation)."""
    return tuple(jnp.sum(jnp.square(a)).reshape(1) for a in data) \
        if len(data) > 1 else jnp.sum(jnp.square(data[0])).reshape(1)


@register("multi_all_finite", args=("data",), variadic=True)
def _multi_all_finite(*data, num_arrays=1, init_output=True):
    """AMP overflow check (reference: ``all_finite.cc``): 1 if every
    element of every array is finite."""
    ok = jnp.array(True)
    for a in data:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a)))
    return ok.astype(jnp.float32).reshape(1)


@register("lars_update", args=("weight", "grad", "mom"))
def _lars_update(weight, grad, mom, lr=0.01, momentum=0.9, eta=0.001,
                 epsilon=1e-9, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """LARS layer-wise adaptive SGD (reference: ``optimizer_op.cc`` LARS
    path / ``optimizer/contrib :: LARS``): the learning rate is scaled by
    the trust ratio eta*||w|| / (||g|| + wd*||w|| + eps) per tensor."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w_norm = jnp.sqrt(jnp.sum(jnp.square(weight)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    trust = jnp.where(
        jnp.logical_and(w_norm > 0, g_norm > 0),
        eta * w_norm / (g_norm + wd * w_norm + epsilon), 1.0)
    lr_adj = lr * trust
    new_mom = momentum * mom + lr_adj * (g + wd * weight)
    return weight - new_mom, new_mom


def _multi_groups(data, group_size, num_weights):
    n = num_weights if num_weights > 0 else len(data) // group_size
    return [data[i * group_size:(i + 1) * group_size] for i in range(n)]


@register("multi_sgd_update", args=("data",), variadic=True)
def _multi_sgd_update(*data, lrs=(), wds=(), rescale_grad=1.0,
                      clip_gradient=-1.0, num_weights=-1):
    """Group SGD over interleaved [w0,g0,w1,g1,...] (reference:
    ``optimizer_op.cc :: multi_sgd_update``): one dispatch updates every
    weight -- under jit the whole group fuses into one XLA program."""
    outs = []
    for i, (w, g) in enumerate(_multi_groups(data, 2, num_weights)):
        outs.append(_sgd_update.fcompute(
            w, g, lr=lrs[i], wd=wds[i], rescale_grad=rescale_grad,
            clip_gradient=clip_gradient))
    return tuple(outs)


@register("multi_sgd_mom_update", args=("data",), variadic=True)
def _multi_sgd_mom_update(*data, lrs=(), wds=(), momentum=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          num_weights=-1):
    """Group momentum SGD over [w0,g0,m0,w1,g1,m1,...]; returns
    (w0',w1',...,m0',m1',...) (reference: ``multi_sgd_mom_update``)."""
    ws, ms = [], []
    for i, (w, g, m) in enumerate(_multi_groups(data, 3, num_weights)):
        nw, nm = _sgd_mom_update.fcompute(
            w, g, m, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        ws.append(nw)
        ms.append(nm)
    return tuple(ws + ms)


@register("multi_mp_sgd_update", args=("data",), variadic=True)
def _multi_mp_sgd_update(*data, lrs=(), wds=(), rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=-1):
    """Group multi-precision SGD over [w0,g0,w32_0,...]; returns
    (w...,w32...) (reference: ``multi_mp_sgd_update``)."""
    ws, w32s = [], []
    for i, (w, g, w32) in enumerate(_multi_groups(data, 3, num_weights)):
        nw, nw32 = _mp_sgd_update.fcompute(
            w, g, w32, lr=lrs[i], wd=wds[i], rescale_grad=rescale_grad,
            clip_gradient=clip_gradient)
        ws.append(nw)
        w32s.append(nw32)
    return tuple(ws + w32s)


@register("multi_lars", args=("lrs", "weights_sum_sq", "grads_sum_sq", "wds"))
def _multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
                eps=1e-9, rescale_grad=1.0):
    """Vectorized LARS trust-ratio lr adjustment over stacked per-tensor
    norms (reference: ``multi_lars.cc``)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    trust = jnp.where(
        jnp.logical_and(w_norm > 0, g_norm > 0),
        eta * w_norm / (g_norm + wds * w_norm + eps), 1.0)
    return lrs * trust
