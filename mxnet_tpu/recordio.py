"""RecordIO: the reference's packed-record file format.

TPU-native port of ``python/mxnet/recordio.py :: MXRecordIO,
MXIndexedRecordIO, IRHeader, pack/unpack, pack_img/unpack_img`` and the
dmlc-core record framing (``3rdparty/dmlc-core/include/dmlc/recordio.h``):

    [kMagic u32][(cflag<<29)|length u32][payload][pad to 4B]

cflag: 0 = whole record, 1 = first chunk, 2 = middle, 3 = last -- records
larger than one chunk are split; magic is escaped inside payloads by
chunking.  ``.idx`` sidecar: "key\\toffset\\n" per record.
"""
from __future__ import annotations

import ctypes
import io
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

kMagic = 0xCED7230A
_HEADER_FMT = "<IfQQ"  # flag, label, id, id2
_HEADER_SIZE = struct.calcsize(_HEADER_FMT)

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])


class MXRecordIO:
    """Sequential record reader/writer (reference: ``MXRecordIO``)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("invalid flag %r" % self.flag)

    def close(self):
        if self.record is not None:
            self.record.close()
            self.record = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.record.tell()

    _MAX_CHUNK = (1 << 29) - 1

    def _write_chunk(self, cflag, buf):
        self.record.write(struct.pack("<I", kMagic))
        self.record.write(struct.pack("<I", (cflag << 29) | len(buf)))
        self.record.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def write(self, buf):
        if not self.writable:
            raise MXNetError("not opened for writing")
        # The length field is 29 bits; larger payloads split into
        # cflag 1 (first) / 2 (middle) / 3 (last) chunks, matching the
        # dmlc recordio framing, so the reader never desynchronizes.
        if len(buf) <= self._MAX_CHUNK:
            self._write_chunk(0, buf)
            return
        chunks = [buf[i:i + self._MAX_CHUNK]
                  for i in range(0, len(buf), self._MAX_CHUNK)]
        for i, chunk in enumerate(chunks):
            cflag = 1 if i == 0 else (3 if i == len(chunks) - 1 else 2)
            self._write_chunk(cflag, chunk)

    def read(self):
        if self.writable:
            raise MXNetError("not opened for reading")
        data = b""
        while True:
            hdr = self.record.read(8)
            if len(hdr) < 8:
                if data:
                    # EOF in the middle of a multi-chunk record (chunks
                    # seen but no cflag-3 terminator): truncated file.
                    raise MXNetError(
                        "corrupt recordio: EOF inside a chunked record")
                return None
            magic, lrec = struct.unpack("<II", hdr)
            if magic != kMagic:
                raise MXNetError("corrupt recordio: bad magic 0x%x" % magic)
            cflag = lrec >> 29
            length = lrec & ((1 << 29) - 1)
            payload = self.record.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.record.read(pad)
            data += payload
            if cflag in (0, 3):
                return data


class MXIndexedRecordIO(MXRecordIO):
    """Indexed random-access reader/writer (reference:
    ``MXIndexedRecordIO``)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) == 2:
                        key = key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
        self.fidx = open(idx_path, "w") if flag == "w" else None

    def close(self):
        super().close()
        if getattr(self, "fidx", None) is not None:
            self.fidx.close()
            self.fidx = None

    def seek(self, idx):
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack a header + payload into a record string (reference: ``pack``)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_HEADER_FMT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        hdr = struct.pack(_HEADER_FMT, label.size, 0.0, header.id, header.id2) \
            + label.tobytes()
    return hdr + s


def unpack(s):
    """Unpack a record into (IRHeader, payload) (reference: ``unpack``)."""
    flag, label, id_, id2 = struct.unpack(_HEADER_FMT, s[:_HEADER_SIZE])
    s = s[_HEADER_SIZE:]
    if flag > 0:
        label = np.frombuffer(s[:flag * 4], np.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an image array into a record (reference: ``pack_img``)."""
    from PIL import Image
    buf = io.BytesIO()
    arr = np.asarray(img)
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    if arr.ndim == 2:
        pil = Image.fromarray(arr, "L")
    else:
        pil = Image.fromarray(arr[:, :, :3], "RGB")
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    kw = {"quality": quality} if fmt == "JPEG" else {}
    pil.save(buf, fmt, **kw)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=1):
    """Decode a record into (IRHeader, HWC uint8 image array)."""
    from PIL import Image
    header, img_bytes = unpack(s)
    pil = Image.open(io.BytesIO(img_bytes))
    if iscolor:
        pil = pil.convert("RGB")
    else:
        pil = pil.convert("L")
    arr = np.asarray(pil)
    if arr.ndim == 2 and iscolor:
        arr = np.stack([arr] * 3, axis=-1)
    return header, arr
