"""RecordIO: the reference's packed-record file format.

TPU-native port of ``python/mxnet/recordio.py :: MXRecordIO,
MXIndexedRecordIO, IRHeader, pack/unpack, pack_img/unpack_img`` and the
dmlc-core record framing (``3rdparty/dmlc-core/include/dmlc/recordio.h``):

    [kMagic u32][(cflag<<29)|length u32][payload][pad to 4B]

cflag: 0 = whole record, 1 = first chunk, 2 = middle, 3 = last -- records
larger than one chunk are split; magic is escaped inside payloads by
chunking.  ``.idx`` sidecar: "key\\toffset\\n" per record.
"""
from __future__ import annotations

import ctypes
import io
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

kMagic = 0xCED7230A
_HEADER_FMT = "<IfQQ"  # flag, label, id, id2
_HEADER_SIZE = struct.calcsize(_HEADER_FMT)

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])


def _native_lib():
    from ._native import load
    return load()


class MXRecordIO:
    """Sequential record reader/writer (reference: ``MXRecordIO``).

    IO runs through the C++ engine (``_native/recordio_native.cc`` --
    buffered framing, thread-pooled batch reads) when the native library
    is available, with a byte-identical pure-Python fallback.
    """

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self._nh = None          # native handle
        self.open()

    def open(self):
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise MXNetError("invalid flag %r" % self.flag)
        lib = _native_lib()
        if lib is not None:
            h = lib.rio_open(self.uri.encode(), 1 if self.writable else 0)
            if not h:
                raise MXNetError("cannot open %r" % self.uri)
            self._nh = h
            self.record = True   # sentinel: "open"
            return
        self.record = open(self.uri, "wb" if self.writable else "rb")

    def close(self):
        if self._nh is not None:
            _native_lib().rio_close(self._nh)
            self._nh = None
            self.record = None
        elif self.record is not None:
            self.record.close()
            self.record = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._nh is not None:
            # a buffered write may not be visible to ftell-reported file
            # offsets used by the .idx sidecar, so tell() is exact: the
            # native side tracks the logical position through the buffer
            return int(_native_lib().rio_tell(self._nh))
        return self.record.tell()

    _MAX_CHUNK = (1 << 29) - 1

    def _write_chunk(self, cflag, buf):
        self.record.write(struct.pack("<I", kMagic))
        self.record.write(struct.pack("<I", (cflag << 29) | len(buf)))
        self.record.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def write(self, buf):
        if not self.writable:
            raise MXNetError("not opened for writing")
        if self._nh is not None:
            if _native_lib().rio_write(self._nh, bytes(buf),
                                       len(buf)) != 0:
                raise MXNetError("recordio write failed")
            return
        # The length field is 29 bits; larger payloads split into
        # cflag 1 (first) / 2 (middle) / 3 (last) chunks, matching the
        # dmlc recordio framing, so the reader never desynchronizes.
        if len(buf) <= self._MAX_CHUNK:
            self._write_chunk(0, buf)
            return
        chunks = [buf[i:i + self._MAX_CHUNK]
                  for i in range(0, len(buf), self._MAX_CHUNK)]
        for i, chunk in enumerate(chunks):
            cflag = 1 if i == 0 else (3 if i == len(chunks) - 1 else 2)
            self._write_chunk(cflag, chunk)

    def read(self):
        if self.writable:
            raise MXNetError("not opened for reading")
        if self._nh is not None:
            lib = _native_lib()
            out = ctypes.c_void_p()
            n = lib.rio_read(self._nh, ctypes.byref(out))
            if n == -1:
                return None
            if n < 0:
                raise MXNetError("corrupt recordio: bad frame")
            data = ctypes.string_at(out, n)
            lib.rio_free(out)
            return data
        data = b""
        while True:
            hdr = self.record.read(8)
            if len(hdr) < 8:
                if data:
                    # EOF in the middle of a multi-chunk record (chunks
                    # seen but no cflag-3 terminator): truncated file.
                    raise MXNetError(
                        "corrupt recordio: EOF inside a chunked record")
                return None
            magic, lrec = struct.unpack("<II", hdr)
            if magic != kMagic:
                raise MXNetError("corrupt recordio: bad magic 0x%x" % magic)
            cflag = lrec >> 29
            length = lrec & ((1 << 29) - 1)
            payload = self.record.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.record.read(pad)
            data += payload
            if cflag in (0, 3):
                return data


class MXIndexedRecordIO(MXRecordIO):
    """Indexed random-access reader/writer (reference:
    ``MXIndexedRecordIO``)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) == 2:
                        key = key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
        self.fidx = open(idx_path, "w") if flag == "w" else None

    def close(self):
        super().close()
        if getattr(self, "fidx", None) is not None:
            self.fidx.close()
            self.fidx = None

    def seek(self, idx):
        if self._nh is not None:
            if _native_lib().rio_seek(self._nh, self.idx[idx]) != 0:
                raise MXNetError("seek failed for key %r" % (idx,))
            return
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def read_batch(self, keys, nthreads=4):
        """Read many records concurrently (reference: the threaded
        record loader in ``iter_image_recordio_2.cc``).

        Routing is measured, not assumed: on a single-core host (or
        nthreads<=1) the buffered sequential Python reads win -- the
        native path pays a per-record malloc+memcpy+ctypes round-trip
        that costs ~2-3x a warm-cache ``read_idx`` loop (the r4->r5
        ``pipeline_raw_uint8`` regression).  The native thread pool is
        engaged only where its parallel IO can actually pay: multicore
        hosts with several reader threads.
        """
        lib = _native_lib()
        if lib is None or self.writable or nthreads <= 1 \
                or (os.cpu_count() or 1) <= 1:
            return [self.read_idx(k) for k in keys]
        n = len(keys)
        offsets = (ctypes.c_long * n)(*[self.idx[k] for k in keys])
        bufs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_long * n)()
        rc = lib.rio_read_batch(self.uri.encode(), offsets, n, bufs, lens,
                                int(nthreads))
        # harvest/free EVERY allocated buffer before raising: an early
        # raise would leak the rest of the batch's native heap
        out, bad = [], None
        for i in range(n):
            if lens[i] < 0 or bufs[i] is None:
                if bad is None:
                    bad = keys[i]
                out.append(None)
            else:
                out.append(ctypes.string_at(bufs[i], lens[i]))
            if bufs[i]:
                lib.rio_free(bufs[i])
        if rc != 0:
            raise MXNetError("cannot open %r for batch read" % self.uri)
        if bad is not None:
            raise MXNetError("corrupt record at key %r" % (bad,))
        return out

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack a header + payload into a record string (reference: ``pack``)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_HEADER_FMT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        hdr = struct.pack(_HEADER_FMT, label.size, 0.0, header.id, header.id2) \
            + label.tobytes()
    return hdr + s


def unpack(s):
    """Unpack a record into (IRHeader, payload) (reference: ``unpack``)."""
    header, view = _unpack_view(s)
    return header, bytes(view)


def _unpack_view(s):
    """``unpack`` returning the payload as a zero-copy memoryview.

    The hot decode paths use this: for raw-pixel records the public
    ``unpack``'s payload slice copies the whole image (~150 KB at
    224x224x3) per record, which costs ~25% of the raw pipeline's
    epoch time.  The view aliases ``s`` -- callers must not outlive it.
    """
    flag, label, id_, id2 = struct.unpack_from(_HEADER_FMT, s, 0)
    view = memoryview(s)[_HEADER_SIZE:]
    if flag > 0:
        # copy the (tiny) label floats: callers retain labels long
        # after the record, and a zero-copy label would pin the whole
        # record's bytes alive per sample
        label = np.frombuffer(bytes(view[:flag * 4]), np.float32)
        view = view[flag * 4:]
    return IRHeader(flag, label, id_, id2), view


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an image array into a record (reference: ``pack_img``)."""
    from PIL import Image
    buf = io.BytesIO()
    arr = np.asarray(img)
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    if arr.ndim == 2:
        pil = Image.fromarray(arr, "L")
    else:
        pil = Image.fromarray(arr[:, :, :3], "RGB")
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    kw = {"quality": quality} if fmt == "JPEG" else {}
    pil.save(buf, fmt, **kw)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=1):
    """Decode a record into (IRHeader, HWC uint8 image array)."""
    from .image.image import _decode_np
    header, img_bytes = unpack(s)
    arr = _decode_np(bytes(img_bytes), iscolor)
    if arr.shape[2] == 1 and iscolor:
        arr = np.repeat(arr, 3, axis=2)
    return header, arr
