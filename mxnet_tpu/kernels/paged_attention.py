"""Registry entry + selection point for decode-step paged attention.

The kernel bodies live in ``ops/pallas/paged_attention.py`` (one query
token per slot, online softmax across the slot's block-table blocks);
this module promotes them into the kernel tier with the standard
contract: ``registry.choose`` is the ONE selection point, the XLA
gather-then-softmax reference is the fallback and the numerics oracle,
and on non-TPU backends a forced Pallas path runs in ``interpret=True``
mode so tier-1 exercises the real kernel body.

:func:`paged_attention` is the call surface the generative decode model
uses -- selection happens at trace time, so the decision is baked into
each compiled decode executable like every other static op param.
"""
from __future__ import annotations

from .registry import KernelSpec, register_kernel


def _supports(heads=0, head_dim=0, block_size=0, **_kw):
    if heads >= 1 and head_dim >= 1 and block_size >= 1:
        return True, ""
    return False, ("paged attention needs positive heads/head_dim/"
                   "block_size (heads=%r, head_dim=%r, block_size=%r)"
                   % (heads, head_dim, block_size))


def _xla_reference(q, k_cache, v_cache, block_tables, context_lens,
                   scale=1.0):
    from ..ops.pallas.paged_attention import paged_attention_reference
    return paged_attention_reference(q, k_cache, v_cache, block_tables,
                                     context_lens, scale=scale)


register_kernel(KernelSpec(
    name="paged_attention",
    doc="Decode-step attention over a paged KV cache "
        "(ops/pallas/paged_attention.py): one query token per slot "
        "walks its block table with online softmax, so decode HBM "
        "traffic is the slot's live context only -- no contiguous "
        "(or padded-to-max) K/V copy per step.  XLA fallback gathers "
        "the table's blocks and runs a masked softmax.",
    categories=("gather", "conv_dot"),
    remedies=(),
    supports=_supports,
    xla_ref=_xla_reference,
))


def paged_attention(q, k_cache, v_cache, block_tables, context_lens,
                    scale=1.0, use_pallas=None):
    """THE decode-attention entry: select pallas-vs-XLA through the
    registry and run it.  ``q`` (slots, heads, d); per-layer cache
    slabs (num_blocks, block_size, heads, d); ``block_tables`` (slots,
    max_blocks) int32; ``context_lens`` (slots, 1) int32."""
    from . import registry as _registry
    heads, head_dim = int(q.shape[1]), int(q.shape[2])
    block_size = int(k_cache.shape[1])
    choice = _registry.choose("paged_attention", force=use_pallas,
                              heads=heads, head_dim=head_dim,
                              block_size=block_size)
    if choice.use_pallas:
        from ..ops.pallas.paged_attention import paged_attention_pallas
        return paged_attention_pallas(q, k_cache, v_cache, block_tables,
                                      context_lens, scale=scale,
                                      interpret=choice.interpret)
    return _xla_reference(q, k_cache, v_cache, block_tables,
                          context_lens, scale=scale)
