"""Pallas kernel registry + selection policy (the fused-operator half
of the blueprint: "fused operators ... become Pallas custom-calls").

One place decides, per kernel and call shape, whether the hand-written
Pallas implementation or the XLA reference path runs -- replacing the
per-call-site ``use_pallas`` branching that used to live in
``ops/transformer.py``.  The policy, in order:

1. ``MXNET_TPU_KERNELS=0``  -> XLA fallback everywhere (kill switch).
2. Pallas unimportable       -> XLA fallback (CPU-only minimal builds).
3. The kernel's ``supports`` predicate rejects the call shape (e.g.
   flash attention needs seq divisible by the block sizes, fused BN
   needs channels-last) -> XLA fallback with the reason recorded.
4. ``MXNET_TPU_KERNELS`` unset (auto): the kernel's ``auto_predicate``
   gates profitability (flash attention's measured seq>=256 crossover;
   the BN fusion sites and the bucketed optimizer stay off -- they are
   opt-in tier features), then the Pallas path is selected only when
   the default backend is a TPU.
5. ``MXNET_TPU_KERNELS=1``: the Pallas path is forced; on a non-TPU
   backend the kernel runs in ``interpret=True`` mode so tier-1 tests
   exercise the REAL kernel bodies instead of the fallback.

``remedy_for(kind)`` maps a perf-audit advisory kind (docs/perf_lint.md)
to the registered kernel that addresses it -- ``perf_audit()`` attaches
it to each advisory so "unfused-elementwise >= 15%" names its fix.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..base import MXNetError

__all__ = ["KernelSpec", "KernelChoice", "register_kernel", "get",
           "list_kernels", "mode", "enabled", "available", "choose",
           "remedy_for", "describe"]


def _has_pallas() -> bool:
    # module-level probe (monkeypatch target for the fallback-proof
    # tests/CI stage: patching this to False must drive every choice to
    # the XLA path)
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except Exception:  # pragma: no cover - pallas ships with jax
        return False


def _backend() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:  # pragma: no cover
        return "cpu"


@dataclass(frozen=True)
class KernelChoice:
    """One selection decision: which implementation runs and why."""
    use_pallas: bool
    interpret: bool
    reason: str

    def __bool__(self) -> bool:
        return self.use_pallas


@dataclass
class KernelSpec:
    """One registered Pallas kernel with its XLA fallback contract."""
    name: str
    doc: str
    # HLO categories whose traffic the kernel removes (mxprof vocabulary)
    categories: Tuple[str, ...] = ()
    # perf-audit advisory kinds this kernel is the remedy for
    remedies: Tuple[str, ...] = ()
    # (**shape_kwargs) -> (ok, reason): correctness constraints only
    supports: Optional[Callable] = None
    # (**shape_kwargs) -> bool: profitability gate for auto mode
    auto_predicate: Optional[Callable] = None
    # the XLA reference implementation (fallback + numerics oracle)
    xla_ref: Optional[Callable] = None
    extra: Dict = field(default_factory=dict)

    def __repr__(self):
        return "KernelSpec(%s)" % self.name


KERNELS: Dict[str, KernelSpec] = {}


def register_kernel(spec: KernelSpec) -> KernelSpec:
    if spec.name in KERNELS and KERNELS[spec.name] is not spec:
        raise MXNetError("duplicate kernel registration %r" % spec.name)
    KERNELS[spec.name] = spec
    return spec


def _ensure_registered():
    # importing the kernel modules registers their specs; lazy so that
    # `import mxnet_tpu` does not pull pallas machinery upfront
    from . import (flash_attention, fused_bn_relu,  # noqa: F401
                   optimizer_update, paged_attention)


def get(name: str) -> KernelSpec:
    _ensure_registered()
    try:
        return KERNELS[name]
    except KeyError:
        raise MXNetError("unknown kernel %r; registered: %s"
                         % (name, ", ".join(sorted(KERNELS)))) from None


def list_kernels() -> List[str]:
    _ensure_registered()
    return sorted(KERNELS)


def mode() -> str:
    """'auto' (env unset), 'off' (MXNET_TPU_KERNELS=0), 'on' (any other
    value) -- read per call so tests/bench can flip the tier around a
    trace (decisions are baked into each compiled program at trace
    time, like every other static op param)."""
    raw = os.environ.get("MXNET_TPU_KERNELS", "")
    if raw == "":
        return "auto"
    return "off" if raw == "0" else "on"


def enabled() -> bool:
    """Whether the Pallas tier may be selected at all."""
    return mode() != "off" and _has_pallas()


def available() -> bool:
    """Whether Pallas itself is importable on this build."""
    return _has_pallas()


def choose(name: str, force: Optional[bool] = None, **shape_kw) \
        -> KernelChoice:
    """THE selection point: decide pallas-vs-XLA for one kernel call.

    ``force`` mirrors the legacy per-op ``use_pallas`` tri-state:
    ``True`` forces the Pallas path (still subject to availability and
    the correctness ``supports`` gate; interpret mode on non-TPU),
    ``False`` forces the XLA fallback, ``None`` applies the env policy.
    """
    spec = get(name)
    if force is False:
        return KernelChoice(False, False, "caller forced XLA path")
    m = mode()
    if force is None and m == "off":
        return KernelChoice(False, False, "MXNET_TPU_KERNELS=0")
    if not _has_pallas():
        return KernelChoice(False, False,
                            "pallas unavailable -> XLA fallback")
    if spec.supports is not None:
        ok, why = spec.supports(**shape_kw)
        if not ok:
            return KernelChoice(False, False, why)
    if force is None and m == "auto" and spec.auto_predicate is not None \
            and not spec.auto_predicate(**shape_kw):
        return KernelChoice(False, False,
                            "auto policy declined (%s)" % name)
    backend = _backend()
    if backend == "tpu":
        return KernelChoice(True, False, "tpu backend")
    if force or m == "on":
        return KernelChoice(
            True, True,
            "interpret-mode kernel on %s backend" % backend)
    return KernelChoice(False, False,
                        "auto: %s backend -> XLA fallback" % backend)


def remedy_for(kind: str) -> Optional[str]:
    """The registered kernel remedying a perf-audit advisory ``kind``
    (e.g. ``'unfused-elementwise' -> 'kernels.fused_bn_relu'``), or
    None when no kernel covers it."""
    _ensure_registered()
    for name in sorted(KERNELS):
        if kind in KERNELS[name].remedies:
            return "kernels." + name
    return None


def describe() -> Dict[str, Dict]:
    """{name: {doc, categories, remedies, choice}} -- the fallback
    matrix docs/kernels.md renders, with each kernel's current
    no-shape-constraints selection decision."""
    _ensure_registered()
    out = {}
    for name, spec in sorted(KERNELS.items()):
        ch = choose(name) if spec.supports is None else None
        out[name] = {
            "doc": spec.doc,
            "categories": list(spec.categories),
            "remedies": list(spec.remedies),
            "mode": mode(),
            "choice": None if ch is None else
            {"use_pallas": ch.use_pallas, "interpret": ch.interpret,
             "reason": ch.reason},
        }
    return out
