"""Registry entry for the flash-attention kernels.

The kernel bodies live in ``ops/pallas/flash_attention.py`` (blockwise
online-softmax forward AND backward, O(seq*d) HBM); this module
promotes them into the kernel tier: ONE ``registry.choose``
selection point replaces the five scattered ``use_pallas`` branches
that used to live in ``ops/transformer.py``, and the auto-mode
profitability gate carries the measured v5e crossover (seq >= 256,
below which XLA's fused materialized-scores path wins -- see
``ops/transformer.py`` for the per-seq numbers).
"""
from __future__ import annotations

from .registry import KernelSpec, register_kernel

# measured v5e crossover (BERT-base bf16 train, r3): seq 128 pallas 93k
# vs xla 117k tok/s; seq 256 111k vs 107k; seq 1024 81k vs 60k
AUTO_MIN_SEQ = 256


def _supports(seq=0, block_q=256, block_k=256, **_kw):
    bq, bk = min(block_q, seq), min(block_k, seq)
    if bq > 0 and seq % bq == 0 and seq % bk == 0:
        return True, ""
    return False, ("flash attention needs seq divisible by the block "
                   "sizes (seq=%d, block_q=%d, block_k=%d)"
                   % (seq, block_q, block_k))


def _auto(seq=0, **_kw):
    return seq >= AUTO_MIN_SEQ


def _xla_reference(q, k, v, causal=False, scale=1.0):
    from ..ops.transformer import _attention_reference
    return _attention_reference(q, k, v, causal, scale)


register_kernel(KernelSpec(
    name="flash_attention",
    doc="Blockwise online-softmax attention, forward AND backward "
        "(ops/pallas/flash_attention.py): scores never leave VMEM, "
        "HBM traffic O(seq*d) instead of O(seq^2) both directions; "
        "optional padding mask.  Auto mode applies the measured "
        "seq>=256 crossover and selects Pallas on TPU only.",
    categories=("elementwise_fusion", "conv_dot"),
    remedies=("memory-bound",),
    supports=_supports,
    auto_predicate=_auto,
    xla_ref=_xla_reference,
))
