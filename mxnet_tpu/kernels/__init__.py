"""``mxnet_tpu.kernels`` -- the Pallas custom-kernel tier.

A registry of hand-written Pallas TPU kernels with automatic XLA
fallback (docs/kernels.md).  Three kernels ship through it:

- ``fused_bn_relu``: NHWC-native fused BatchNorm+ReLU (training
  forward AND backward; bf16 activations, fp32 batch statistics),
  wired into the gluon ``HybridSequential`` BatchNorm+Activation
  fusion sites behind ``MXNET_TPU_KERNELS=1``.
- ``flash_attention``: the blockwise online-softmax attention kernels
  (``ops/pallas/flash_attention.py``), promoted out of ad-hoc
  ``use_pallas`` branches into ONE registry selection point.
- ``bucket_optimizer``: LARS/LAMB trust-ratio + momentum update over
  one concatenated per-dtype buffer (shared ``mxnet_tpu.bucketing``
  grouping), replacing the per-parameter elementwise-kernel swarm in
  the compiled train step.
- ``paged_attention``: decode-step attention over the generative
  serving tier's paged KV cache (``ops/pallas/paged_attention.py``):
  one query token per slot walks its block table with online softmax;
  XLA fallback gathers the table's blocks and masks.

Selection policy (``registry.choose``): ``MXNET_TPU_KERNELS`` unset =
auto (Pallas only where measured profitable, on TPU), ``1`` = forced
(interpret mode on CPU so tier-1 exercises the real kernel bodies),
``0`` = XLA everywhere.
"""
from .registry import (KernelChoice, KernelSpec, available, choose,
                       describe, enabled, get, list_kernels, mode,
                       register_kernel, remedy_for)

__all__ = ["KernelChoice", "KernelSpec", "available", "choose",
           "describe", "enabled", "get", "list_kernels", "mode",
           "register_kernel", "remedy_for"]
