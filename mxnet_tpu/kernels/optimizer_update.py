"""Bucket-flattened optimizer update (Pallas/TPU): LARS/LAMB trust
ratios + momentum over ONE concatenated per-dtype buffer.

The compiled train step used to dispatch one ``lars_update`` /
``lamb_update_phase1/2`` program fragment PER PARAMETER -- for
ResNet-50 that is ~160 tiny elementwise kernels per step (the
"per-parameter elementwise-kernel swarm" PR 10's audit flags as
top-level unfused-elementwise traffic).  Here the parameter set is
grouped by dtype with the shared ``mxnet_tpu.bucketing`` helper (the
same grouping the PR-9 host collectives use), each group's weights/
grads/momenta flatten into one contiguous buffer, per-tensor trust
ratios compute as small fused reductions, and the elementwise update
runs as ONE pass over the flat buffer -- a Pallas VMEM kernel when the
registry selects it, the identical jnp math otherwise.

Per-tensor semantics are preserved exactly: LARS trust ratios (and the
skip-list's plain-momentum path, including its opposite momentum sign
convention, so checkpointed state stays interchangeable with the eager
per-parameter updates) and LAMB's bias correction + r1/r2 trust bounds
all ride per-element vectors expanded from per-tensor scalars.

Custom-vjp backward: the flat updates are ``jax.custom_vjp`` functions
whose backward replays the XLA math through ``jax.vjp`` (the
layernorm-kernel pattern) -- differentiable for meta-learning uses,
with the trust ratio treated as part of the per-element ``lr`` input.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..bucketing import dtype_groups, flatten_group, split_group
from .registry import KernelSpec, choose, mode, register_kernel

try:  # pallas import kept lazy-safe: CPU-only builds fall back to XLA
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

LANE = 128


def _pad2d(v, lane=LANE):
    """Flat (n,) -> (rows, lane) zero-padded, for the 2-D tiling the
    TPU vector memory wants."""
    n = v.shape[0]
    rows = -(-n // lane)
    pad = rows * lane - n
    if pad:
        v = jnp.pad(v, (0, pad))
    return v.reshape(rows, lane)


def _best_block(rows, want):
    b = max(1, min(want, rows))
    while rows % b:
        b -= 1
    return b


def _expand(per_tensor, sizes, total):
    """Expand a (P,) per-tensor vector onto the flat (S,) buffer.
    ``jnp.repeat`` with a static ``total_repeat_length`` computes the
    gather plan on device from the (P,) sizes -- no S-sized host
    constant baked into the program (ResNet-50's S is ~25M)."""
    return jnp.repeat(per_tensor, jnp.asarray(sizes),
                      total_repeat_length=total)


# ----------------------------------------------------------------------
# flat LARS / momentum update
# ----------------------------------------------------------------------

def _lars_math(w, g, m, lr, wd, sign, rescale, momentum, clip):
    """One fused elementwise pass over the flat buffer: per-element
    ``lr`` already carries the per-tensor trust ratio; ``sign`` +1 for
    LARS-convention momentum, -1 for the skip-list's sgd-convention
    momentum (identical trajectories, sign-compatible stored state)."""
    wf = w.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    mf = m.astype(jnp.float32)
    gr = gf * rescale
    if clip is not None and clip > 0:
        gr = jnp.clip(gr, -clip, clip)
    step = lr * (gr + wd * wf)
    nm = momentum * mf + sign * step
    nw = wf - sign * nm
    return nw.astype(w.dtype), nm.astype(m.dtype)


def _lars_flat_kernel(w_ref, g_ref, m_ref, lr_ref, wd_ref, sg_ref,
                      rs_ref, w_out, m_out, *, momentum, clip):
    rescale = rs_ref[0, 0]
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    gr = g * rescale
    if clip is not None and clip > 0:
        gr = jnp.clip(gr, -clip, clip)
    step = lr_ref[...] * (gr + wd_ref[...] * w)
    nm = momentum * m + sg_ref[...] * step
    nw = w - sg_ref[...] * nm
    w_out[...] = nw.astype(w_out.dtype)
    m_out[...] = nm.astype(m_out.dtype)


@functools.partial(jax.jit, static_argnames=("momentum", "clip",
                                             "block_rows", "interpret"))
def lars_flat_pallas(w, g, m, lr, wd, sign, rescale, momentum=0.9,
                     clip=0.0, block_rows=64, interpret=False):
    """The flat momentum update as ONE Pallas kernel over the padded
    (rows, 128) view of the concatenated buffer."""
    n = w.shape[0]
    ops2d = [_pad2d(v) for v in (w, g, m, lr, wd, sign)]
    rows, lane = ops2d[0].shape
    block_rows = _best_block(rows, block_rows)
    row = pl.BlockSpec((block_rows, lane), lambda i: (i, 0))
    scalar = pl.BlockSpec((1, 1), lambda i: (0, 0))
    rs = jnp.asarray(rescale, jnp.float32).reshape(1, 1)
    nw, nm = pl.pallas_call(
        functools.partial(_lars_flat_kernel, momentum=momentum,
                          clip=clip),
        out_shape=[jax.ShapeDtypeStruct(ops2d[0].shape, w.dtype),
                   jax.ShapeDtypeStruct(ops2d[2].shape, m.dtype)],
        grid=(rows // block_rows,),
        in_specs=[row] * 6 + [scalar],
        out_specs=[row, row],
        interpret=interpret,
    )(*ops2d, rs)
    return nw.reshape(-1)[:n], nm.reshape(-1)[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _flat_lars(w, g, m, lr, wd, sign, rescale, momentum, clip,
               use_pallas, interpret):
    if use_pallas:
        return lars_flat_pallas(w, g, m, lr, wd, sign, rescale,
                                momentum=momentum, clip=clip,
                                interpret=interpret)
    return _lars_math(w, g, m, lr, wd, sign, rescale, momentum, clip)


def _flat_lars_fwd(w, g, m, lr, wd, sign, rescale, momentum, clip,
                   use_pallas, interpret):
    out = _flat_lars(w, g, m, lr, wd, sign, rescale, momentum, clip,
                     use_pallas, interpret)
    return out, (w, g, m, lr, wd, sign, rescale)


def _flat_lars_bwd(momentum, clip, use_pallas, interpret, res, cts):
    # backward = XLA math replay (the layernorm-kernel pattern): exact
    # autodiff of the update formula, trust ratio riding the lr input
    w, g, m, lr, wd, sign, rescale = res
    _, vjp = jax.vjp(
        lambda *ins: _lars_math(*ins, momentum, clip),
        w, g, m, lr, wd, sign, rescale)
    return vjp(cts)


_flat_lars.defvjp(_flat_lars_fwd, _flat_lars_bwd)


def lars_bucket_update(ws, gs, ms, lrs, wds, skips, momentum=0.9,
                       eta=0.001, epsilon=1e-9, rescale=1.0, clip=None,
                       choice=None):
    """Bucket-flattened LARS over parameter lists.

    ``ws``/``gs``/``ms``: weights, gradients, momenta (raw arrays, same
    order); ``lrs``/``wds``: per-tensor scalars (python or traced);
    ``skips``: static per-tensor bools selecting the plain-momentum
    path (bias/gamma/beta, the reference's skip list).  Returns
    ``(new_ws, new_ms)`` in input order."""
    ch = choice if choice is not None else choose("bucket_optimizer")
    clipv = float(clip) if clip is not None and clip > 0 else 0.0
    rs = jnp.asarray(rescale, jnp.float32)
    new_ws = [None] * len(ws)
    new_ms = [None] * len(ws)
    for _dtype, idxs in dtype_groups(ws):
        lr_t, wd_t = [], []
        for i in idxs:
            gf = gs[i].astype(jnp.float32) * rs
            if clipv > 0:
                gf = jnp.clip(gf, -clipv, clipv)
            if skips[i]:
                trust = jnp.float32(1.0)
            else:
                wn = jnp.sqrt(jnp.sum(
                    jnp.square(ws[i].astype(jnp.float32))))
                gn = jnp.sqrt(jnp.sum(jnp.square(gf)))
                trust = jnp.where(
                    jnp.logical_and(wn > 0, gn > 0),
                    eta * wn / (gn + wds[i] * wn + epsilon), 1.0)
            lr_t.append(jnp.asarray(lrs[i], jnp.float32) * trust)
            wd_t.append(jnp.asarray(wds[i], jnp.float32))
        sizes = [int(ws[i].size) for i in idxs]
        total = sum(sizes)
        lr_vec = _expand(jnp.stack(lr_t), sizes, total)
        wd_vec = _expand(jnp.stack(wd_t), sizes, total)
        sign_vec = _expand(
            jnp.asarray(np.where([skips[i] for i in idxs], -1.0, 1.0)
                        .astype(np.float32)), sizes, total)
        W = flatten_group(ws, idxs, jnp)
        G = flatten_group(gs, idxs, jnp)
        M = flatten_group(ms, idxs, jnp)
        nW, nM = _flat_lars(W, G, M, lr_vec, wd_vec, sign_vec, rs,
                            float(momentum), clipv, ch.use_pallas,
                            ch.interpret)
        shapes = [ws[i].shape for i in idxs]
        for i, pw, pm in zip(idxs, split_group(nW, shapes),
                             split_group(nM, shapes)):
            new_ws[i] = pw
            new_ms[i] = pm
    return new_ws, new_ms


# ----------------------------------------------------------------------
# flat LAMB: phase 1 elementwise over the flat buffer, per-tensor
# trust via segment reductions, phase 2 elementwise
# ----------------------------------------------------------------------

def _lamb1_math(w, g, m, v, wd, scalars, beta1, beta2, eps, clip):
    rescale, bc1, bc2 = scalars[0], scalars[1], scalars[2]
    wf = w.astype(jnp.float32)
    gr = g.astype(jnp.float32) * rescale
    if clip is not None and clip > 0:
        gr = jnp.clip(gr, -clip, clip)
    nm = beta1 * m.astype(jnp.float32) + (1 - beta1) * gr
    nv = beta2 * v.astype(jnp.float32) + (1 - beta2) * gr * gr
    gw = (nm * bc1) / (jnp.sqrt(nv * bc2) + eps) + wd * wf
    return gw, nm.astype(m.dtype), nv.astype(v.dtype)


def _lamb1_kernel(w_ref, g_ref, m_ref, v_ref, wd_ref, sc_ref,
                  gw_ref, nm_ref, nv_ref, *, beta1, beta2, eps, clip):
    rescale = sc_ref[0, 0]
    bc1 = sc_ref[0, 1]
    bc2 = sc_ref[0, 2]
    w = w_ref[...].astype(jnp.float32)
    gr = g_ref[...].astype(jnp.float32) * rescale
    if clip is not None and clip > 0:
        gr = jnp.clip(gr, -clip, clip)
    nm = beta1 * m_ref[...].astype(jnp.float32) + (1 - beta1) * gr
    nv = beta2 * v_ref[...].astype(jnp.float32) + (1 - beta2) * gr * gr
    gw = (nm * bc1) / (jnp.sqrt(nv * bc2) + eps) + wd_ref[...] * w
    gw_ref[...] = gw
    nm_ref[...] = nm.astype(nm_ref.dtype)
    nv_ref[...] = nv.astype(nv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("beta1", "beta2", "eps",
                                             "clip", "block_rows",
                                             "interpret"))
def lamb_phase1_pallas(w, g, m, v, wd, scalars, beta1=0.9, beta2=0.999,
                       eps=1e-6, clip=0.0, block_rows=64,
                       interpret=False):
    n = w.shape[0]
    ops2d = [_pad2d(x) for x in (w, g, m, v, wd)]
    rows, lane = ops2d[0].shape
    block_rows = _best_block(rows, block_rows)
    row = pl.BlockSpec((block_rows, lane), lambda i: (i, 0))
    sc = pl.BlockSpec((1, 3), lambda i: (0, 0))
    gw, nm, nv = pl.pallas_call(
        functools.partial(_lamb1_kernel, beta1=beta1, beta2=beta2,
                          eps=eps, clip=clip),
        out_shape=[jax.ShapeDtypeStruct(ops2d[0].shape, jnp.float32),
                   jax.ShapeDtypeStruct(ops2d[2].shape, m.dtype),
                   jax.ShapeDtypeStruct(ops2d[3].shape, v.dtype)],
        grid=(rows // block_rows,),
        in_specs=[row] * 5 + [sc],
        out_specs=[row, row, row],
        interpret=interpret,
    )(*ops2d, scalars.reshape(1, 3))
    return (gw.reshape(-1)[:n], nm.reshape(-1)[:n], nv.reshape(-1)[:n])


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _flat_lamb1(w, g, m, v, wd, scalars, beta1, beta2, eps, clip,
                use_pallas, interpret):
    if use_pallas:
        return lamb_phase1_pallas(w, g, m, v, wd, scalars, beta1=beta1,
                                  beta2=beta2, eps=eps, clip=clip,
                                  interpret=interpret)
    return _lamb1_math(w, g, m, v, wd, scalars, beta1, beta2, eps, clip)


def _flat_lamb1_fwd(w, g, m, v, wd, scalars, beta1, beta2, eps, clip,
                    use_pallas, interpret):
    out = _flat_lamb1(w, g, m, v, wd, scalars, beta1, beta2, eps, clip,
                      use_pallas, interpret)
    return out, (w, g, m, v, wd, scalars)


def _flat_lamb1_bwd(beta1, beta2, eps, clip, use_pallas, interpret,
                    res, cts):
    w, g, m, v, wd, scalars = res
    _, vjp = jax.vjp(
        lambda *ins: _lamb1_math(*ins, beta1, beta2, eps, clip),
        w, g, m, v, wd, scalars)
    return vjp(cts)


_flat_lamb1.defvjp(_flat_lamb1_fwd, _flat_lamb1_bwd)


def lamb_bucket_update(ws, gs, means, variances, lrs, wds, t, beta1=0.9,
                       beta2=0.999, epsilon=1e-6, bias_correction=True,
                       lower_bound=None, upper_bound=None, rescale=1.0,
                       clip=None, choice=None):
    """Bucket-flattened LAMB: phase-1 update direction over the flat
    buffer (Pallas when selected), per-tensor ``r1``/``r2`` trust norms
    via segment reductions, phase-2 trust-scaled step over the flat
    buffer.  ``t`` is the (traced) step count for bias correction.
    Returns ``(new_ws, new_means, new_vars)`` in input order."""
    ch = choice if choice is not None else choose("bucket_optimizer")
    clipv = float(clip) if clip is not None and clip > 0 else 0.0
    bc1 = 1.0 / (1.0 - beta1 ** t) if bias_correction else 1.0
    bc2 = 1.0 / (1.0 - beta2 ** t) if bias_correction else 1.0
    scalars = jnp.stack([jnp.asarray(rescale, jnp.float32),
                         jnp.asarray(bc1, jnp.float32),
                         jnp.asarray(bc2, jnp.float32)])
    new_ws = [None] * len(ws)
    new_means = [None] * len(ws)
    new_vars = [None] * len(ws)
    for _dtype, idxs in dtype_groups(ws):
        sizes = [int(ws[i].size) for i in idxs]
        nseg = len(idxs)
        total = sum(sizes)
        seg = _expand(jnp.arange(nseg), sizes, total)
        lr_vec = _expand(jnp.stack([jnp.asarray(lrs[i], jnp.float32)
                                    for i in idxs]), sizes, total)
        wd_vec = _expand(jnp.stack([jnp.asarray(wds[i], jnp.float32)
                                    for i in idxs]), sizes, total)
        W = flatten_group(ws, idxs, jnp)
        G = flatten_group(gs, idxs, jnp)
        Mn = flatten_group(means, idxs, jnp)
        V = flatten_group(variances, idxs, jnp)
        gw, nm, nv = _flat_lamb1(W, G, Mn, V, wd_vec, scalars,
                                 float(beta1), float(beta2),
                                 float(epsilon), clipv, ch.use_pallas,
                                 ch.interpret)
        # per-tensor trust ratio (lamb_update_phase2 semantics)
        wf = W.astype(jnp.float32)
        r1 = jnp.sqrt(jax.ops.segment_sum(wf * wf, seg,
                                          num_segments=nseg,
                                          indices_are_sorted=True))
        r2 = jnp.sqrt(jax.ops.segment_sum(gw * gw, seg,
                                          num_segments=nseg,
                                          indices_are_sorted=True))
        if lower_bound is not None and lower_bound > 0:
            r1 = jnp.maximum(r1, lower_bound)
        if upper_bound is not None and upper_bound > 0:
            r1 = jnp.minimum(r1, upper_bound)
        ratio = jnp.where(jnp.logical_or(r1 == 0, r2 == 0), 1.0, r1 / r2)
        nW = (wf - lr_vec * jnp.take(ratio, seg) * gw).astype(W.dtype)
        shapes = [ws[i].shape for i in idxs]
        for i, pw, pm, pv in zip(idxs, split_group(nW, shapes),
                                 split_group(nm, shapes),
                                 split_group(nv, shapes)):
            new_ws[i] = pw
            new_means[i] = pm
            new_vars[i] = pv
    return new_ws, new_means, new_vars


# ----------------------------------------------------------------------
# TrainStep integration (called inside the traced step under
# parallel.data_parallel._scalar_feed)
# ----------------------------------------------------------------------

def bucket_supported(opt) -> bool:
    """Whether the optimizer has a bucket-flattened update."""
    from ..optimizer import LAMB, LARS
    return type(opt) in (LARS, LAMB) and not opt.multi_precision


def bucket_active(opt) -> bool:
    """The compiled-train-step gate: the bucketed update replaces the
    per-parameter loop only under MXNET_TPU_KERNELS=1 (the XLA-vs-
    Pallas choice for the flat pass is the registry's, inside)."""
    return mode() == "on" and bucket_supported(opt)


def bucket_update(opt, items):
    """Fused update for the compiled train step: ``items`` is
    ``[(index, weight_val, grad_val, state_val)]`` with raw (traced)
    arrays; must run under ``_scalar_feed`` so ``opt._get_lr`` /
    ``_get_wd`` / ``_index_update_count`` yield the traced per-step
    feeds.  Returns ``({index: new_weight}, {index: new_state})`` with
    states in the optimizer's own structure."""
    from ..optimizer import LARS
    idxs = [i for i, _w, _g, _s in items]
    ws = [w for _i, w, _g, _s in items]
    gs = [g for _i, _w, g, _s in items]
    lrs = [opt._get_lr(i) for i in idxs]
    wds = [opt._get_wd(i) for i in idxs]
    rescale = opt.rescale_grad
    clip = opt.clip_gradient
    ch = choose("bucket_optimizer")
    if type(opt) is LARS:
        ms = [s for _i, _w, _g, s in items]
        skips = [bool(opt._skip_lars(i)) for i in idxs]
        nws, nms = lars_bucket_update(
            ws, gs, ms, lrs, wds, skips, momentum=opt.momentum,
            eta=opt.eta, epsilon=opt.epsilon, rescale=rescale,
            clip=clip, choice=ch)
        return ({i: w for i, w in zip(idxs, nws)},
                {i: m for i, m in zip(idxs, nms)})
    means = [s[0] for _i, _w, _g, s in items]
    variances = [s[1] for _i, _w, _g, s in items]
    t = opt._index_update_count[idxs[0]]
    nws, nmeans, nvars = lamb_bucket_update(
        ws, gs, means, variances, lrs, wds, t, beta1=opt.beta1,
        beta2=opt.beta2, epsilon=opt.epsilon,
        bias_correction=opt.bias_correction,
        lower_bound=opt.lower_bound, upper_bound=opt.upper_bound,
        rescale=rescale, clip=clip, choice=ch)
    return ({i: w for i, w in zip(idxs, nws)},
            {i: (m, v) for i, m, v in zip(idxs, nmeans, nvars)})


register_kernel(KernelSpec(
    name="bucket_optimizer",
    doc="LARS/LAMB trust-ratio + momentum update over one concatenated "
        "per-dtype buffer (shared mxnet_tpu.bucketing grouping): the "
        "per-parameter elementwise-kernel swarm in the compiled train "
        "step becomes one flat pass (Pallas VMEM kernel when selected) "
        "plus small fused trust-norm reductions.  Opt-in via "
        "MXNET_TPU_KERNELS=1.",
    categories=("elementwise_fusion",),
    remedies=(),
    supports=None,
    auto_predicate=lambda **_kw: False,
))
