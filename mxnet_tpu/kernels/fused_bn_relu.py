"""Fused BatchNorm+ReLU (Pallas/TPU): NHWC-native, training forward
AND backward.

The PR-10 audits name transpose/layout traffic and unfused-elementwise
HLO as the top cost categories on the ResNet path; the BN->ReLU pair is
the hottest such site (one full activation read for the normalize, one
for the scale/shift, one for the relu when XLA declines to fuse across
the running-stat outputs).  This kernel is the remedy: the per-channel
batch statistics reduce in fp32 (XLA -- two independent reductions fuse
into one read pass, the same shifted one-pass moments as
``ops/nn._batch_norm``), then ONE Pallas VMEM pass applies
normalize + affine + relu, keeping activations bf16 in HBM with fp32
math in registers.  The custom-vjp backward mirrors it: the two
gradient reductions run in XLA (one read pass), then one Pallas VMEM
pass produces dx from the fused training-mode BN backward formula with
the relu mask folded in.

Channels-last only (NHWC-native): any other ``axis`` falls back to the
XLA reference path via the registry choice -- moving the channel axis
would pay exactly the transpose traffic the kernel exists to remove.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import KernelSpec, choose, register_kernel

try:  # pallas import kept lazy-safe: CPU-only builds fall back to XLA
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _best_block(rows, want):
    b = max(1, min(want, rows))
    while rows % b:
        b -= 1          # largest divisor <= requested block
    return b


# ----------------------------------------------------------------------
# forward apply: out = relu(x * scale + offset), one VMEM pass
# ----------------------------------------------------------------------

def _apply_fwd_kernel(x_ref, s_ref, o_ref, out_ref):
    x = x_ref[...].astype(jnp.float32)          # (block_rows, C)
    y = x * s_ref[...] + o_ref[...]             # (1, C) broadcasts
    out_ref[...] = jnp.maximum(y, 0.0).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def bn_relu_apply_pallas(x2d, scale, offset, block_rows=256,
                         interpret=False):
    """``relu(x2d * scale + offset)`` over (rows, C); ``scale``/
    ``offset`` are the folded per-channel (1, C) fp32 vectors
    ``gamma*rsqrt(var+eps)`` and ``beta - mean*gamma*rsqrt(var+eps)``."""
    rows, c = x2d.shape
    block_rows = _best_block(rows, block_rows)
    vec = pl.BlockSpec((1, c), lambda i: (0, 0))
    return pl.pallas_call(
        _apply_fwd_kernel,
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
                  vec, vec],
        out_specs=pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, scale, offset)


# ----------------------------------------------------------------------
# backward apply: dx from the fused BN(+relu-mask) training formula,
# one VMEM pass (the reductions c1/c2 arrive precomputed)
# ----------------------------------------------------------------------

def _apply_bwd_kernel(x_ref, dy_ref, y_ref, a_ref, m_ref, i_ref,
                      c1_ref, c2_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    y = y_ref[...].astype(jnp.float32)
    dyr = jnp.where(y > 0.0, dy, 0.0)           # relu mask folded in
    xhat = (x - m_ref[...]) * i_ref[...]
    dx = a_ref[...] * (dyr - c1_ref[...] - xhat * c2_ref[...])
    dx_ref[...] = dx.astype(dx_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def bn_relu_bwd_pallas(x2d, dy2d, y2d, a, mean, inv, c1, c2,
                       block_rows=256, interpret=False):
    """dx of fused BN+ReLU over (rows, C).  Per-channel (1, C) fp32
    vectors: ``a = gamma*inv``; ``c1``/``c2`` the mean-reduced
    ``dyr`` / ``dyr*xhat`` (zeros in inference mode, where the batch
    statistics are constants)."""
    rows, c = x2d.shape
    block_rows = _best_block(rows, block_rows)
    row_spec = pl.BlockSpec((block_rows, c), lambda i: (i, 0))
    vec = pl.BlockSpec((1, c), lambda i: (0, 0))
    return pl.pallas_call(
        _apply_bwd_kernel,
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        grid=(rows // block_rows,),
        in_specs=[row_spec, row_spec, row_spec, vec, vec, vec, vec, vec],
        out_specs=row_spec,
        interpret=interpret,
    )(x2d, dy2d, y2d, a, mean, inv, c1, c2)


# ----------------------------------------------------------------------
# custom-vjp apply stage (mean/var arrive stop_gradiented; the
# training-mode stats backward is folded into dx here)
# ----------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _bn_relu_apply(x2d, gamma_eff, beta, mean, var, eps, batch_stats,
                   use_pallas, interpret):
    inv = lax.rsqrt(var + eps)
    scale = (gamma_eff * inv)[None, :]
    offset = (beta.astype(jnp.float32) - mean * gamma_eff * inv)[None, :]
    if use_pallas:
        return bn_relu_apply_pallas(x2d, scale, offset,
                                    interpret=interpret)
    xf = x2d.astype(jnp.float32)
    return jnp.maximum(xf * scale + offset, 0.0).astype(x2d.dtype)


def _bn_relu_apply_fwd(x2d, gamma_eff, beta, mean, var, eps, batch_stats,
                       use_pallas, interpret):
    out = _bn_relu_apply(x2d, gamma_eff, beta, mean, var, eps,
                         batch_stats, use_pallas, interpret)
    return out, (x2d, out, gamma_eff, beta, mean, var)


def _bn_relu_apply_bwd(eps, batch_stats, use_pallas, interpret, res, dy):
    x2d, y2d, gamma_eff, beta, mean, var = res
    inv = lax.rsqrt(var + eps)
    xf = x2d.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    dyr = jnp.where(y2d.astype(jnp.float32) > 0.0, dyf, 0.0)
    xhat = (xf - mean[None, :]) * inv[None, :]
    # the two reductions fuse into ONE read pass over (x, dy, y)
    sum_dyr = jnp.sum(dyr, axis=0)
    sum_dyr_xhat = jnp.sum(dyr * xhat, axis=0)
    m = x2d.shape[0]
    if batch_stats:
        # training: mean/var were computed from THIS batch upstream
        # (fed in stop_gradiented), so their backward is folded here --
        # dx = a*(dyr - mean(dyr) - xhat*mean(dyr*xhat))
        c1 = sum_dyr / m
        c2 = sum_dyr_xhat / m
    else:
        c1 = jnp.zeros_like(sum_dyr)
        c2 = jnp.zeros_like(sum_dyr_xhat)
    a = gamma_eff * inv
    if use_pallas:
        dx = bn_relu_bwd_pallas(x2d, dy, y2d, a[None, :], mean[None, :],
                                inv[None, :], c1[None, :], c2[None, :],
                                interpret=interpret)
    else:
        dx = (a[None, :] * (dyr - c1[None, :] - xhat * c2[None, :])) \
            .astype(x2d.dtype)
    dgamma = sum_dyr_xhat.astype(gamma_eff.dtype)
    dbeta = sum_dyr.astype(beta.dtype)
    return (dx, dgamma, dbeta, jnp.zeros_like(mean), jnp.zeros_like(var))


_bn_relu_apply.defvjp(_bn_relu_apply_fwd, _bn_relu_apply_bwd)


# ----------------------------------------------------------------------
# full fused op (stats + apply); the ops-registry fcompute delegates here
# ----------------------------------------------------------------------

def xla_reference(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
                  momentum=0.9, fix_gamma=True, use_global_stats=False,
                  axis=1, training=False):
    """The XLA fallback AND numerics oracle: relu over the registered
    BatchNorm op (identical statistics math)."""
    from ..ops.nn import _batch_norm
    out, nm, nv = _batch_norm.fcompute(
        data, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats, axis=axis, training=training)
    return jax.nn.relu(out), nm, nv


def fused_bn_relu(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
                  momentum=0.9, fix_gamma=True, use_global_stats=False,
                  axis=1, training=False):
    """Fused BatchNorm+ReLU: ``(out, new_moving_mean, new_moving_var)``
    with the same functional contract as the ``BatchNorm`` op plus the
    relu epilogue.  Kernel-vs-fallback is decided ONCE here through the
    registry (``choose('fused_bn_relu')``)."""
    ch = choose("fused_bn_relu", axis=axis, ndim=data.ndim)
    if not ch.use_pallas:
        return xla_reference(data, gamma, beta, moving_mean, moving_var,
                             eps=eps, momentum=momentum,
                             fix_gamma=fix_gamma,
                             use_global_stats=use_global_stats,
                             axis=axis, training=training)
    c = data.shape[-1]
    x2d = data.reshape(-1, c)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    gf = g.astype(jnp.float32)
    batch_stats = bool(training) and not use_global_stats
    if batch_stats:
        # shifted one-pass moments, same math as ops/nn._batch_norm:
        # the two reductions are independent -> ONE read pass; the
        # moving-mean shift bounds catastrophic cancellation
        shift = lax.stop_gradient(moving_mean.astype(jnp.float32))
        y = x2d.astype(jnp.float32) - shift[None, :]
        mean_y = jnp.mean(y, axis=0)
        m2 = jnp.mean(y * y, axis=0)
        var = jnp.maximum(m2 - mean_y * mean_y, 0.0)
        mean = mean_y + shift
        # EMA blended in fp32, stored back at the aux dtype (same
        # discipline as ops/nn._batch_norm): a weak-typed
        # ``momentum * moving_mean`` would round at bf16 per step
        new_mean = (momentum * moving_mean.astype(jnp.float32)
                    + (1 - momentum) * mean).astype(moving_mean.dtype)
        new_var = (momentum * moving_var.astype(jnp.float32)
                   + (1 - momentum) * var).astype(moving_var.dtype)
    else:
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        new_mean, new_var = moving_mean, moving_var
    out2d = _bn_relu_apply(x2d, gf, beta,
                           lax.stop_gradient(mean),
                           lax.stop_gradient(var),
                           float(eps), batch_stats, True, ch.interpret)
    return (out2d.reshape(data.shape), lax.stop_gradient(new_mean),
            lax.stop_gradient(new_var))


def _supports(axis=1, ndim=4, **_kw):
    if axis in (-1, ndim - 1):
        return True, ""
    return False, ("fused_bn_relu is NHWC-native (channels-last); "
                   "axis=%d of a %d-d input falls back to XLA -- "
                   "moving the channel axis would pay the transpose "
                   "traffic the kernel removes" % (axis, ndim))


register_kernel(KernelSpec(
    name="fused_bn_relu",
    doc="NHWC-native fused BatchNorm+ReLU: fp32 batch statistics (one "
        "XLA read pass), one Pallas VMEM pass for normalize+affine+"
        "relu, custom-vjp backward with the relu mask and stats "
        "backward folded into one dx pass.  Wired into the gluon "
        "HybridSequential BatchNorm+Activation fusion sites behind "
        "MXNET_TPU_KERNELS=1.",
    categories=("elementwise_fusion", "transpose_layout"),
    remedies=("unfused-elementwise", "transpose-share"),
    supports=_supports,
    xla_ref=xla_reference,
))
