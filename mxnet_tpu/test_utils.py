"""Testing toolkit.

TPU-native port of the reference's op-correctness contract
(``python/mxnet/test_utils.py :: assert_almost_equal,
check_numeric_gradient, check_consistency, default_context``).
``check_consistency`` runs one op on a list of contexts/dtypes and
cross-compares -- the reference's cpu-vs-gpu pattern applied cpu-vs-tpu.
"""
from __future__ import annotations

import numpy as np

from . import autograd
from . import context as _ctx_mod
from .base import MXNetError
from .ndarray import NDArray, array
from .ops.registry import get_op
from .ndarray.ndarray import invoke


def default_context():
    """TPU if present, else cpu (reference: ``default_context``)."""
    if _ctx_mod.num_tpus() > 0:
        return _ctx_mod.tpu(0)
    return _ctx_mod.cpu(0)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg="%s vs %s" % names)


def rand_ndarray(shape, ctx=None, dtype="float32", scale=1.0):
    return array(np.random.normal(0, scale, size=shape).astype(dtype), ctx=ctx)


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4,
                           wrt=None):
    """Finite-difference check of recorded gradients.

    ``fn(*NDArrays) -> scalar NDArray``; compares tape backward against
    central differences (reference: ``check_numeric_gradient``).
    """
    nds = [array(i) if not isinstance(i, NDArray) else i for i in inputs]
    wrt = list(range(len(nds))) if wrt is None else wrt
    for i in wrt:
        nds[i].attach_grad()
    with autograd.record():
        out = fn(*nds)
    out.backward()
    for i in wrt:
        base = nds[i].asnumpy().astype(np.float64)
        num = np.zeros_like(base)
        flat = base.ravel()
        numflat = num.ravel()
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = fn(*[array(base.astype(np.float32)) if k == i else nds[k]
                      for k in range(len(nds))]).asscalar()
            flat[j] = orig - eps
            fm = fn(*[array(base.astype(np.float32)) if k == i else nds[k]
                      for k in range(len(nds))]).asscalar()
            flat[j] = orig
            numflat[j] = (fp - fm) / (2 * eps)
        got = nds[i].grad.asnumpy()
        np.testing.assert_allclose(got, num, rtol=rtol, atol=atol,
                                   err_msg="gradient wrt input %d" % i)


def check_consistency(op_name, tensor_inputs, params=None, ctx_list=None,
                      rtol=5e-3, atol=1e-5):
    """Run one op on every context in ``ctx_list`` and cross-compare
    (reference: ``check_consistency`` cpu-vs-gpu; here cpu-vs-tpu).

    Default tolerances allow for the TPU MXU's bf16-accumulated fp32
    matmul precision (the reference similarly relaxes per-dtype for gpu).
    """
    params = params or {}
    if ctx_list is None:
        ctx_list = [_ctx_mod.cpu()]
        if _ctx_mod.num_tpus():
            ctx_list.append(_ctx_mod.tpu())
    op = get_op(op_name)
    results = []
    for ctx in ctx_list:
        args = [array(t, ctx=ctx) for t in tensor_inputs]
        out = invoke(op, args, dict(params))
        outs = out if isinstance(out, list) else [out]
        results.append([o.asnumpy() for o in outs])
    ref = results[0]
    for got, ctx in zip(results[1:], ctx_list[1:]):
        for r, g in zip(ref, got):
            np.testing.assert_allclose(
                g, r, rtol=rtol, atol=atol,
                err_msg="%s inconsistent between %s and %s"
                        % (op_name, ctx_list[0], ctx))


class DummyIter:
    """Infinite constant-batch iterator (reference: ``DummyIter``)."""

    def __init__(self, batch):
        self.batch = batch

    def __iter__(self):
        while True:
            yield self.batch
