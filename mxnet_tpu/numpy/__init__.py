"""``mx.np``: the NumPy-compatible array API (reference:
``python/mxnet/numpy/`` -- MXNet 2.x's primary interface).

Design: ``mx.np.ndarray`` IS an ``mx.nd.NDArray`` (a view subclass
sharing the device buffer and autograd tape state), so the two worlds
mix freely and everything here differentiates.  Functions route through
the SAME op registry as ``mx.nd`` -- each call hits the persistent
per-op jit cache, not a private dispatch path.  Only naming and
semantics differ: NumPy names (``concatenate``, ``matmul``, ``.T``),
NumPy broadcasting everywhere, NumPy default dtypes.
"""
from __future__ import annotations

import numpy as _onp

from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from ..ndarray import ndarray as _nd_mod
from ..ops.registry import get_op

__all__ = ["ndarray", "array", "asarray", "zeros", "ones", "empty",
           "full", "eye",
           "arange", "linspace", "concatenate", "stack", "split", "dot",
           "matmul", "tensordot", "einsum", "where", "maximum", "minimum",
           "clip", "abs", "exp", "log", "sqrt", "square", "power", "sum",
           "mean", "var", "std", "prod", "max", "min", "argmax", "argmin",
           "reshape", "transpose", "expand_dims", "squeeze", "tile",
           "repeat", "flip", "cumsum", "isnan", "isinf", "isfinite",
           "sort", "argsort", "take", "vstack", "hstack", "dstack",
           "pi", "e", "inf", "nan", "newaxis", "random"]

pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None


class ndarray(NDArray):
    """NumPy-flavored NDArray view (reference: ``numpy.ndarray`` in
    ``python/mxnet/numpy/multiarray.py``)."""

    @property
    def T(self):
        return transpose(self)

    def __repr__(self):
        return "array(%s)" % _onp.array2string(self.asnumpy(),
                                               separator=", ")

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return _view(super().reshape(shape))

    def item(self):
        return self.asnumpy().item()

    def tolist(self):
        return self.asnumpy().tolist()

    @property
    def size(self):
        return int(_onp.prod(self.shape)) if self.shape else 1

    def copy(self):
        return _view(super().copy())

    def astype(self, dtype):
        return _view(super().astype(dtype))

    def mean(self, axis=None, keepdims=False):
        return mean(self, axis=axis, keepdims=keepdims)

    def sum(self, axis=None, keepdims=False):
        return sum(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return min(self, axis=axis, keepdims=keepdims)


def _view(a):
    """Reinterpret an NDArray as mx.np.ndarray, sharing buffer + tape."""
    if isinstance(a, ndarray):
        return a
    if isinstance(a, NDArray):
        out = ndarray.__new__(ndarray)
        out._data = a._data
        out._grad = a._grad
        out._grad_req = getattr(a, "_grad_req", "write")
        out._ag_node = a._ag_node
        out._ag_out_index = a._ag_out_index
        return out
    return a


def _views(x):
    if isinstance(x, list):
        return [_view(v) for v in x]
    return _view(x)


def _call(opname, tensor_args, **params):
    return _views(_nd_mod.invoke(get_op(opname), tensor_args, params))


# ----------------------------------------------------------------------
# creation
# ----------------------------------------------------------------------

def array(object, dtype=None, ctx=None):
    """numpy semantics: array() COPIES (use asarray for a view)."""
    from ..ndarray import array as nd_array
    if isinstance(object, NDArray):
        object = object.asnumpy()
    arr = _onp.asarray(object)
    if dtype is None:
        # numpy default dtype rules, float64 capped at float32 (x64 off)
        dtype = _onp.float32 if arr.dtype in (_onp.float64,) else arr.dtype
    return _view(nd_array(arr, ctx=ctx, dtype=dtype))


def asarray(object, dtype=None, ctx=None):
    """View when possible: an existing NDArray shares buffer + tape."""
    if isinstance(object, NDArray) and dtype is None:
        return _view(object)
    return array(object, dtype=dtype, ctx=ctx)


def zeros(shape, dtype="float32", ctx=None):
    from ..ndarray import zeros as nd_zeros
    return _view(nd_zeros(shape if isinstance(shape, (tuple, list))
                          else (shape,), ctx=ctx, dtype=dtype))


def ones(shape, dtype="float32", ctx=None):
    from ..ndarray import ones as nd_ones
    return _view(nd_ones(shape if isinstance(shape, (tuple, list))
                         else (shape,), ctx=ctx, dtype=dtype))


def empty(shape, dtype="float32", ctx=None):
    return zeros(shape, dtype, ctx)


def full(shape, fill_value, dtype="float32", ctx=None):
    from ..ndarray import full as nd_full
    return _view(nd_full(shape if isinstance(shape, (tuple, list))
                         else (shape,), fill_value, ctx=ctx, dtype=dtype))


def eye(N, M=None, k=0, dtype="float32", ctx=None):
    return array(_onp.eye(N, M, k, dtype=dtype), ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    a = _onp.arange(start, stop, step, dtype=dtype)
    return array(a, ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None):
    return array(_onp.linspace(start, stop, num, endpoint=endpoint,
                               dtype=dtype or _onp.float32), ctx=ctx)


# ----------------------------------------------------------------------
# joining / shaping
# ----------------------------------------------------------------------

def concatenate(seq, axis=0):
    return _call("Concat", list(seq), dim=axis)


def stack(seq, axis=0):
    return _call("stack", list(seq), axis=axis)


def split(ary, indices_or_sections, axis=0):
    if not isinstance(indices_or_sections, int):
        raise MXNetError("mx.np.split supports integer sections")
    outs = _call("split", [ary], num_outputs=indices_or_sections,
                 axis=axis)
    return outs if isinstance(outs, list) else [outs]


def reshape(a, newshape):
    return _view(a.reshape(newshape) if isinstance(a, NDArray)
                 else array(a).reshape(newshape))


def transpose(a, axes=None):
    params = {} if axes is None else {"axes": tuple(axes)}
    return _call("transpose", [a], **params)


def expand_dims(a, axis):
    return _call("expand_dims", [a], axis=axis)


def squeeze(a, axis=None):
    params = {} if axis is None else {"axis": axis}
    return _call("squeeze", [a], **params)


def tile(a, reps):
    return _call("tile", [a], reps=tuple(reps)
                 if isinstance(reps, (list, tuple)) else (reps,))


def repeat(a, repeats, axis=None):
    params = {"repeats": repeats}
    if axis is not None:
        params["axis"] = axis
    return _call("repeat", [a], **params)


def flip(a, axis=None):
    if axis is None:
        # numpy semantics: flip over ALL axes
        axis = tuple(range(len(a.shape)))
    return _call("flip", [a], axis=axis)


# ----------------------------------------------------------------------
# math (generated thin wrappers over registry ops)
# ----------------------------------------------------------------------

def _unary_fn(opname, npname=None):
    def fn(a):
        return _call(opname, [a])
    fn.__name__ = npname or opname
    return fn


abs = _unary_fn("abs")
exp = _unary_fn("exp")
log = _unary_fn("log")
log2 = _unary_fn("log2")
log10 = _unary_fn("log10")
sqrt = _unary_fn("sqrt")
square = _unary_fn("square")
sin = _unary_fn("sin")
cos = _unary_fn("cos")
tan = _unary_fn("tan")
tanh = _unary_fn("tanh")
sign = _unary_fn("sign")
floor = _unary_fn("floor")
ceil = _unary_fn("ceil")
isnan = _unary_fn("isnan")
isinf = _unary_fn("isinf")
isfinite = _unary_fn("isfinite")
negative = _unary_fn("negative")


def power(a, b):
    if isinstance(b, (int, float)):
        return _call("_power_scalar", [a], scalar=float(b))
    return _call("broadcast_power", [a, b])


def maximum(a, b):
    if isinstance(b, (int, float)):
        return _call("_maximum_scalar", [a], scalar=float(b))
    return _call("broadcast_maximum", [a, b])


def minimum(a, b):
    if isinstance(b, (int, float)):
        return _call("_minimum_scalar", [a], scalar=float(b))
    return _call("broadcast_minimum", [a, b])


def clip(a, a_min, a_max):
    return _call("clip", [a], a_min=a_min, a_max=a_max)


def where(condition, x, y):
    return _call("where", [condition, x, y])


def dot(a, b):
    return _call("dot", [a, b])


def matmul(a, b):
    return _call("matmul", [a, b])


def tensordot(a, b, axes=2):
    return _call("tensordot", [a, b], axes=axes)


def einsum(subscripts, *operands):
    return _call("einsum", list(operands), subscripts=subscripts)


def _reduce_fn(opname, npname):
    def fn(a, axis=None, keepdims=False):
        params = {"keepdims": keepdims}
        if axis is not None:
            params["axis"] = axis
        return _call(opname, [a], **params)
    fn.__name__ = npname
    return fn


sum = _reduce_fn("sum", "sum")
mean = _reduce_fn("mean", "mean")
prod = _reduce_fn("prod", "prod")
max = _reduce_fn("max", "max")
min = _reduce_fn("min", "min")


def var(a, axis=None, ddof=0, keepdims=False):
    params = {"ddof": ddof, "keepdims": keepdims}
    if axis is not None:
        params["axis"] = axis
    return _call("_np_var", [a], **params)


def std(a, axis=None, ddof=0, keepdims=False):
    params = {"ddof": ddof, "keepdims": keepdims}
    if axis is not None:
        params["axis"] = axis
    return _call("_np_std", [a], **params)


def argmax(a, axis=None):
    params = {} if axis is None else {"axis": axis}
    return _call("argmax", [a], **params)


def argmin(a, axis=None):
    params = {} if axis is None else {"axis": axis}
    return _call("argmin", [a], **params)


def cumsum(a, axis=None):
    params = {} if axis is None else {"axis": axis}
    return _call("cumsum", [a], **params)


def sort(a, axis=-1):
    return _call("sort", [a], axis=axis)


def argsort(a, axis=-1):
    return _call("argsort", [a], axis=axis)


def take(a, indices, axis=None):
    idx = indices if isinstance(indices, NDArray) else array(indices)
    if axis is None:
        # numpy semantics: take from the flattened array.  Note:
        # out-of-range indices clip (static-shape gather) rather than
        # raising as numpy does.
        a = reshape(a, (-1,))
        axis = 0
    return _call("take", [a, idx], axis=axis)


def vstack(seq):
    return _call("vstack", list(seq))


def hstack(seq):
    return _call("hstack", list(seq))


def dstack(seq):
    return _call("dstack", list(seq))


# ----------------------------------------------------------------------
# random (reference: python/mxnet/numpy/random.py)
# ----------------------------------------------------------------------

class _Random:
    @staticmethod
    def seed(s):
        from .. import random as rnd
        rnd.seed(s)

    @staticmethod
    def uniform(low=0.0, high=1.0, size=None, ctx=None):
        from ..ndarray import random as nd_random
        size = size if size is not None else ()
        size = size if isinstance(size, (tuple, list)) else (size,)
        return _view(nd_random.uniform(low, high, shape=tuple(size),
                                       ctx=ctx))

    @staticmethod
    def normal(loc=0.0, scale=1.0, size=None, ctx=None):
        from ..ndarray import random as nd_random
        size = size if size is not None else ()
        size = size if isinstance(size, (tuple, list)) else (size,)
        return _view(nd_random.normal(loc, scale, shape=tuple(size),
                                      ctx=ctx))

    @staticmethod
    def randint(low, high=None, size=None, ctx=None):
        from ..ndarray import random as nd_random
        if high is None:
            low, high = 0, low
        size = size if size is not None else ()
        size = size if isinstance(size, (tuple, list)) else (size,)
        return _view(nd_random.randint(low, high, shape=tuple(size),
                                       ctx=ctx))

    @staticmethod
    def rand(*shape):
        return _Random.uniform(size=shape)

    @staticmethod
    def randn(*shape):
        return _Random.normal(size=shape)


random = _Random()
