"""Graph executor.

TPU-native re-design of ``src/executor/graph_executor.cc ::
GraphExecutor`` / ``python/mxnet/executor.py :: Executor``.  The nnvm
passes (InferShape, PlanMemory, AttachOpExecs) collapse into one
``jax.jit`` of the graph walk: XLA does buffer assignment, fusion, and
scheduling.  Backward is the jitted vjp of the same function (replacing
the nnvm Gradient pass), with ``grad_req`` write/add/null honored at the
rebind step.
"""
from __future__ import annotations

import jax

from .base import MXNetError
from .ndarray import NDArray
from .symbol.symbol import _eval_symbol


class Executor:
    """Bound executor (reference: ``Executor.forward/backward/outputs``)."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_names = symbol.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(self.arg_names, args))
        self.arg_dict = dict(args or {})
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(self.arg_names, args_grad))
        self.grad_dict = dict(args_grad or {})
        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in self.arg_names}
        else:
            self.grad_req = dict(grad_req)
        self.aux_dict = dict(aux_states or {})
        self.outputs = []
        self._fwd_jit = None
        self._fwdbwd_jit = None
        self._vjp = None

    def _pure(self, arg_vals):
        class _W:
            def __init__(self, d):
                self._data = d
        feed = {k: _W(v) for k, v in arg_vals.items()}
        outs = _eval_symbol(self._symbol, feed)
        return tuple(o._data for o in outs)

    def forward(self, is_train=False, **kwargs):
        """Run the graph (reference: ``GraphExecutor::RunOps``)."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown input %r" % k)
            self.arg_dict[k]._data = v._data if isinstance(v, NDArray) \
                else v
        arg_vals = {k: v._data for k, v in self.arg_dict.items()}
        if is_train:
            grad_names = [n for n in self.arg_names
                          if self.grad_req.get(n, "null") != "null"]

            def split(av):
                diff = {n: av[n] for n in grad_names}
                nondiff = {n: av[n] for n in av if n not in diff}
                return diff, nondiff

            diff, nondiff = split(arg_vals)
            if self._fwdbwd_jit is None:
                def fwd(diff, nondiff):
                    merged = dict(nondiff)
                    merged.update(diff)
                    return jax.vjp(lambda d: self._pure({**nondiff, **d}),
                                   diff)
                self._fwdbwd_jit = jax.jit(
                    lambda d, nd: jax.vjp(
                        lambda dd: self._pure({**nd, **dd}), d))
                self._bwd_jit = jax.jit(lambda vjp, cts: vjp(cts))
            outs, self._vjp = self._fwdbwd_jit(diff, nondiff)
        else:
            if self._fwd_jit is None:
                self._fwd_jit = jax.jit(self._pure)
            outs = self._fwd_jit(arg_vals)
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        """Reference: ``Executor.backward``; accumulates into the bound
        grad arrays per grad_req."""
        import jax.numpy as jnp
        if self._vjp is None:
            raise MXNetError("backward before forward(is_train=True)")
        if out_grads is None:
            cts = [jnp.ones(o.shape, o.dtype) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = [g._data for g in out_grads]
        (grads,) = self._bwd_jit(self._vjp, tuple(cts))
        for name, g in grads.items():
            req = self.grad_req.get(name, "null")
            if req == "null" or name not in self.grad_dict:
                continue
            tgt = self.grad_dict[name]
            if req == "add":
                tgt._data = tgt._data + g
            else:
                tgt._data = g
        self._vjp = None

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data
            elif not allow_extra_params:
                raise MXNetError("unknown parameter %r" % k)
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._data = v._data
