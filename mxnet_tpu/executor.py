"""Graph executor.

TPU-native re-design of ``src/executor/graph_executor.cc ::
GraphExecutor`` / ``python/mxnet/executor.py :: Executor``.  The nnvm
passes (InferShape, PlanMemory, AttachOpExecs) collapse into one
``jax.jit`` of the graph walk: XLA does buffer assignment, fusion, and
scheduling.  Backward is the jitted vjp of the same function (replacing
the nnvm Gradient pass), with ``grad_req`` write/add/null honored at the
rebind step.

Training forwards run ONE compiled program producing outputs, updated
aux states (BatchNorm running stats write-back), and gradients under the
default head cotangent -- so the ``forward(is_train=True); backward()``
legacy protocol costs a single XLA dispatch per step.  An explicit
``backward(out_grads=...)`` recomputes with the custom cotangent.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from . import profiling as _profiling
from . import telemetry as _telemetry
from .base import MXNetError
from .ndarray import NDArray
from .symbol.symbol import _eval_symbol


class _W:
    __slots__ = ("_data",)

    def __init__(self, d):
        self._data = d


class Executor:
    """Bound executor (reference: ``Executor.forward/backward/outputs``)."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None, group2ctx=None,
                 check=None):
        self._symbol = symbol
        self._ctx = ctx
        self._group2ctx = dict(group2ctx) if group2ctx else None
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            args = dict(zip(self.arg_names, args))
        self.arg_dict = dict(args or {})
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(self.arg_names, args_grad))
        self.grad_dict = dict(args_grad or {})
        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in self.arg_names}
        else:
            self.grad_req = dict(grad_req)
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(self.aux_names, aux_states))
        self.aux_dict = dict(aux_states or {})
        # Opt-in static graph gate (mxnet_tpu.analysis): validate the
        # whole graph -- structure plus shape/dtype propagation over the
        # bound arrays -- before any device time is spent.  Off by
        # default (bind stays cheap); enable per-bind with check=True
        # or globally with MXNET_TPU_GRAPH_CHECK=1.
        if check is None:
            from . import env as _env
            check = _env.get("MXNET_TPU_GRAPH_CHECK")
        if check:
            from .analysis import assert_graph_ok
            shapes = {k: tuple(v.shape)
                      for k, v in {**self.arg_dict, **self.aux_dict}.items()}
            assert_graph_ok(symbol, shapes=shapes or None)
        self.outputs = []
        self._fwd_jit = None
        self._train_jit = None
        self._last_train_args = None
        self._pending_grads = None

    # ------------------------------------------------------------------
    def _pure(self, vals, training):
        """Pure graph walk: name->jax.Array in, (outputs, aux_updates)
        out.  ``training`` is a trace-time static (two jit cache
        entries, like the reference's train/eval CachedOp modes)."""
        from . import autograd
        feed = {k: _W(v) for k, v in vals.items()}
        aux_updates = {} if training else None
        prev = autograd.is_training()
        autograd.set_training(training)
        try:
            outs = _eval_symbol(self._symbol, feed, aux_updates)
        finally:
            autograd.set_training(prev)
        return tuple(o._data for o in outs), aux_updates or {}

    def _all_vals(self):
        vals = {k: v._data for k, v in self.arg_dict.items()}
        vals.update({k: v._data for k, v in self.aux_dict.items()})
        return vals

    # -- ctx_group model parallelism (reference: AttrScope(ctx_group=)
    # + bind(group2ctx=), example/model-parallel-lstm) ----------------
    def _forward_grouped(self):
        """Per-node eager execution with explicit inter-group device
        transfers -- the reference's PlaceDevice semantics (each op runs
        on its group's device, copies inserted at group boundaries).
        The SPMD-native way to split models is mxnet_tpu.parallel's
        TP/PP over a Mesh; this path is the compatibility shim for
        ctx_group graphs."""
        import jax
        from .symbol.symbol import _eval_node_value

        def dev_of(node):
            group = node.attrs.get("ctx_group") if node.attrs else None
            ctx = self._group2ctx.get(group) if group else None
            ctx = ctx or self._ctx
            return ctx.jax_device() if ctx is not None else None

        vals = {}
        feed = self._all_vals()
        for node in self._symbol._topo():
            if node.op is None:
                v = feed.get(node.name)
                if v is None:
                    raise MXNetError("unbound variable %r" % node.name)
                dev = dev_of(node)
                if dev is not None and dev not in v.devices():
                    v = jax.device_put(v, dev)
                vals[(id(node), 0)] = v
                continue
            dev = dev_of(node)
            if dev is not None:
                for src, oi in node.inputs:
                    cur = vals[(id(src), oi)]
                    if dev not in cur.devices():
                        # group boundary: explicit transfer
                        vals[(id(src), oi)] = jax.device_put(cur, dev)
            out = _eval_node_value(node, vals)
            if isinstance(out, tuple):
                for i, o in enumerate(out):
                    vals[(id(node), i)] = o
            else:
                vals[(id(node), 0)] = out
        self.outputs = [NDArray(vals[(id(n), i)])
                        for n, i in self._symbol._outputs]
        return self.outputs

    def forward(self, is_train=False, **kwargs):
        """Run the graph (reference: ``GraphExecutor::RunOps``)."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown input %r" % k)
            self.arg_dict[k]._data = v._data if isinstance(v, NDArray) \
                else v
        if self._group2ctx:
            if is_train:
                raise MXNetError(
                    "group2ctx training is not supported by the compat "
                    "shim (per-op device placement, forward only); use "
                    "mxnet_tpu.parallel tensor/pipeline parallelism for "
                    "SPMD model-parallel training")
            return self._forward_grouped()
        vals = self._all_vals()
        if is_train:
            grad_names = [n for n in self.arg_names
                          if self.grad_req.get(n, "null") != "null"]
            diff = {n: vals[n] for n in grad_names}
            nondiff = {n: v for n, v in vals.items() if n not in diff}
            first = self._train_jit is None
            if first:
                def _train_step(diff, nondiff, cts):
                    def f(dd):
                        return self._pure({**nondiff, **dd}, True)
                    outs, vjp, aux_up = jax.vjp(f, diff, has_aux=True)
                    if cts is None:
                        cts = tuple(jnp.ones(o.shape, o.dtype)
                                    for o in outs)
                    (grads,) = vjp(tuple(cts))
                    return outs, aux_up, grads
                # no donation by design: the legacy forward/backward
                # protocol re-calls this executable with the SAME
                # diff/nondiff buffers (backward(out_grads=...) recompute,
                # arg_dict stays bound across steps) -- donating them
                # would hand XLA buffers the executor still owns.  The
                # donated single-dispatch step is parallel.TrainStep.
                self._train_jit = jax.jit(_train_step)  # mxlint: disable=undonated-train-state
            # first call = trace + XLA compile; time it as the compile
            # event (later calls hit the executable cache)
            t0 = time.perf_counter() if first and _telemetry._ENABLED \
                else None
            outs, aux_up, grads = self._train_jit(diff, nondiff, None)
            if t0 is not None:
                _telemetry.hooks.compile_event(
                    "executor.train", seconds=time.perf_counter() - t0,
                    n_args=len(diff) + len(nondiff))
            if _profiling._ENABLED:
                _profiling.capture_jit(
                    "executor.train", self._train_jit,
                    (diff, nondiff, None),
                    key=("executor", id(self), "train"), kind="executor")
            for name, v in aux_up.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._data = v
            self._last_train_args = (diff, nondiff)
            self._pending_grads = grads
        else:
            first = self._fwd_jit is None
            if first:
                self._fwd_jit = jax.jit(
                    lambda vals: self._pure(vals, False)[0])
            t0 = time.perf_counter() if first and _telemetry._ENABLED \
                else None
            outs = self._fwd_jit(vals)
            if t0 is not None:
                _telemetry.hooks.compile_event(
                    "executor.eval", seconds=time.perf_counter() - t0,
                    n_args=len(vals))
            if _profiling._ENABLED:
                _profiling.capture_jit(
                    "executor.eval", self._fwd_jit, (vals,),
                    key=("executor", id(self), "eval"), kind="executor")
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        """Reference: ``Executor.backward``; accumulates into the bound
        grad arrays per grad_req.  With the default head cotangent the
        gradients were already produced by the training forward's
        compiled program; a custom ``out_grads`` recomputes."""
        if self._last_train_args is None:
            raise MXNetError("backward before forward(is_train=True)")
        if out_grads is None:
            grads = self._pending_grads
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(g._data for g in out_grads)
            diff, nondiff = self._last_train_args
            _, _, grads = self._train_jit(diff, nondiff, cts)
        for name, g in grads.items():
            req = self.grad_req.get(name, "null")
            if req == "null" or name not in self.grad_dict:
                continue
            tgt = self.grad_dict[name]
            if req == "add":
                tgt._data = tgt._data + g
            else:
                tgt._data = g
        self._last_train_args = None
        self._pending_grads = None

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data
            elif not allow_extra_params:
                raise MXNetError("unknown parameter %r" % k)
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._data = v._data
