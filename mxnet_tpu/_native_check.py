"""Importable probe for native-component availability (used by
``mx.runtime.Features()['NATIVE_RECORDIO']``).  Import succeeds only if
the native library is built and loadable."""
from ._native import load

if load() is None:
    raise ImportError("mxnet_tpu native library unavailable")
