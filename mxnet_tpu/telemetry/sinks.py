"""The three shipped telemetry sinks.

- ``JsonlSink``: append-only run log.  Streamed records (event emits,
  timer samples) land as they happen; ``Registry.flush()`` appends the
  aggregate snapshot, so the file is both a timeline and a summary.
- ``prom_text``: Prometheus text exposition of a snapshot -- scrapeable
  or diffable, one metric family per instrument.
- ``summary_table``: human console table (the ``mx.telemetry.summary()``
  surface and the CLI's default rendering).

All three consume the same ``Registry.snapshot()`` record shape, so the
CLI can re-render a JSONL file through either text format offline.
"""
from __future__ import annotations

import json
import os
import re

from .. import sync as _sync

__all__ = ["JsonlSink", "prom_text", "summary_table"]


def _default_rank():
    """This process's rank per the launcher env (0 single-process) --
    every JSONL record is tagged with it so multi-host runs can be
    merged and skew-analyzed offline (``mxtelemetry summarize r0.jsonl
    r1.jsonl ...``)."""
    try:
        return int(os.environ.get("MXNET_TPU_PROC_ID", "0") or 0)
    except ValueError:
        return 0


class JsonlSink:
    """Append telemetry records to ``path`` as one JSON object per line.

    Writes are line-buffered under a lock (instrument hooks may fire
    from DataLoader worker threads); ``flush()`` fsyncs nothing -- a
    telemetry log is advisory, not a WAL.  Every record carries this
    process's ``rank`` (``MXNET_TPU_PROC_ID``), so rank files from one
    multi-host run stay attributable after a merge.
    """

    def __init__(self, path, rank=None):
        self.path = path
        self.rank = _default_rank() if rank is None else int(rank)
        self._lock = _sync.Lock(name="telemetry.jsonl_sink")
        self._f = open(path, "a")

    def write(self, record):
        if "rank" not in record:
            record = dict(record, rank=self.rank)
        line = json.dumps(record, default=_json_default)
        with self._lock:
            if self._f is not None:
                self._f.write(line + "\n")

    def flush(self):
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None


def _json_default(obj):
    """Payloads may carry numpy scalars or dtype objects; degrade to
    strings rather than refuse to log."""
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(obj)


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name):
    return "mxnet_tpu_" + _PROM_BAD.sub("_", name)


def prom_text(snapshot):
    """Render a ``Registry.snapshot()`` list as Prometheus text
    exposition (counters/gauges verbatim; timers as ``_count``/``_sum``
    summaries plus ``le``-labeled buckets; events as counters)."""
    lines = []
    for rec in snapshot:
        kind = rec["kind"].replace("snapshot.", "")
        base = _prom_name(rec["name"])
        if kind == "counter":
            lines.append("# TYPE %s counter" % base)
            lines.append("%s %s" % (base, rec["value"]))
        elif kind == "gauge":
            if rec.get("value") is None:
                continue
            lines.append("# TYPE %s gauge" % base)
            lines.append("%s %s" % (base, rec["value"]))
        elif kind == "timer":
            lines.append("# TYPE %s histogram" % base)
            lines.append("%s_count %s" % (base, rec["count"]))
            lines.append("%s_sum %s" % (base, rec["sum"]))
            acc = 0
            for le, n in sorted(rec.get("buckets", {}).items(),
                                key=lambda kv: float(kv[0])):
                acc += n
                lines.append('%s_bucket{le="%s"} %d' % (base, le, acc))
            lines.append('%s_bucket{le="+Inf"} %d' % (base, rec["count"]))
            # quantile series off the histogram estimator (ISSUE 17):
            # scrapers get p50/p95/p99 without replaying the buckets
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                v = rec.get(key)
                if v is not None:
                    lines.append('%s{quantile="%s"} %s' % (base, q, v))
        elif kind == "event":
            lines.append("# TYPE %s counter" % base)
            lines.append("%s %s" % (base, rec["count"]))
    return "\n".join(lines) + ("\n" if lines else "")


def _fmt_secs(s):
    if s is None:
        return "-"
    if s >= 1.0:
        return "%.3fs" % s
    if s >= 1e-3:
        return "%.2fms" % (s * 1e3)
    return "%.1fus" % (s * 1e6)


def summary_table(snapshot):
    """Console table over a snapshot, grouped by instrument kind."""
    groups = {"counter": [], "gauge": [], "timer": [], "event": []}
    for rec in snapshot:
        kind = rec["kind"].replace("snapshot.", "")
        if kind in groups:
            groups[kind].append(rec)
    out = []

    def header(title, cols):
        out.append(title)
        out.append("  %-44s %s" % cols)
        out.append("  " + "-" * 68)

    if groups["counter"]:
        header("counters", ("name", "value"))
        for r in groups["counter"]:
            out.append("  %-44s %d" % (r["name"], r["value"]))
        out.append("")
    if groups["gauge"]:
        header("gauges", ("name", "last (min/max over n)"))
        for r in groups["gauge"]:
            if r.get("value") is None:
                continue
            out.append("  %-44s %.4g (%.4g/%.4g over %d)"
                       % (r["name"], r["value"], r["min"], r["max"],
                          r["count"]))
        out.append("")
    if groups["timer"]:
        header("timers",
               ("name", "count  mean  p50  p95  p99  min  max  total"))
        for r in groups["timer"]:
            out.append("  %-44s %-6d %s  %s  %s  %s  %s  %s  %s"
                       % (r["name"], r["count"], _fmt_secs(r.get("mean")),
                          _fmt_secs(r.get("p50")), _fmt_secs(r.get("p95")),
                          _fmt_secs(r.get("p99")),
                          _fmt_secs(r.get("min")), _fmt_secs(r.get("max")),
                          _fmt_secs(r.get("sum"))))
        out.append("")
    if groups["event"]:
        header("events", ("name", "count  last payload"))
        for r in groups["event"]:
            payload = r.get("last_payload")
            out.append("  %-44s %-6d %s"
                       % (r["name"], r["count"],
                          json.dumps(payload, default=_json_default)
                          if payload else "-"))
        out.append("")
    return "\n".join(out) if out else "(no telemetry recorded)\n"
