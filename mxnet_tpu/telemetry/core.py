"""Typed runtime instruments and the thread-safe registry behind them.

Design (ISSUE 2 tentpole): ``jax.profiler`` traces (``mx.profiler``) are
post-hoc and TensorBoard-shaped; this module is the always-on,
*queryable* layer -- named Counters/Gauges/Timers/Events cheap enough to
leave enabled for a whole production run and dump as data (JSONL /
Prometheus text / console table, see ``sinks.py``).

Everything here is host-side Python and independent of JAX: creating or
mutating an instrument never touches a device, never syncs, and never
allocates on the hot path beyond a tuple for the streamed record.  The
*enable gate* lives in ``telemetry/__init__.py`` (module flag
``_ENABLED``); instrumented framework modules check that one flag and
skip every call below when it is off.
"""
from __future__ import annotations

import bisect
import time

from .. import sync as _sync

__all__ = ["Counter", "Gauge", "Timer", "Event", "Registry"]

# Ring capacity for per-Event payload history: enough to answer "what
# were the recent retraces" without letting a pathological loop grow
# host memory unboundedly.
_EVENT_RING = 256


class Instrument:
    """Base: a named instrument owned by one Registry."""

    kind = "instrument"

    def __init__(self, name, registry=None):
        self.name = name
        self._registry = registry
        # one role identity for every instrument's lock: the order
        # graph (docs/concurrency.md) reasons about roles, not instances
        self._lock = _sync.Lock(name="telemetry.instrument")

    def _stream(self, record_kind, **fields):
        reg = self._registry
        if reg is not None:
            reg._stream({"kind": record_kind, "name": self.name,
                         "t": time.time(), **fields})

    def snapshot(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError


class Counter(Instrument):
    """Monotonic-by-convention event count (``inc``); ``set`` exists for
    the mx.profiler compatibility surface, which allows absolute writes."""

    kind = "counter"

    def __init__(self, name, registry=None):
        super().__init__(name, registry)
        self._value = 0

    def inc(self, delta=1):
        with self._lock:
            self._value += delta

    def dec(self, delta=1):
        self.inc(-delta)

    def set(self, value):
        with self._lock:
            self._value = value

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return {"kind": "counter", "name": self.name, "value": self._value}

    def reset(self):
        with self._lock:
            self._value = 0


class Gauge(Instrument):
    """Last-written value plus running min/max/count, for quantities
    that go up and down (samples/sec, loss scale, queue depth)."""

    kind = "gauge"

    def __init__(self, name, registry=None):
        super().__init__(name, registry)
        self.reset()

    def set(self, value):
        value = float(value)
        with self._lock:
            self._value = value
            self._count += 1
            self._min = value if self._min is None else min(self._min, value)
            self._max = value if self._max is None else max(self._max, value)

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return {"kind": "gauge", "name": self.name, "value": self._value,
                "count": self._count, "min": self._min, "max": self._max}

    def reset(self):
        with self._lock:
            self._value = None
            self._count = 0
            self._min = None
            self._max = None


# Power-of-2 latency buckets from 1us to ~134s; le-style upper bounds in
# seconds.  Fixed so two runs' histograms merge by index.
_TIMER_BUCKETS = tuple(1e-6 * (2 ** i) for i in range(28))


class Timer(Instrument):
    """Duration histogram: count/sum/min/max plus fixed power-of-2
    buckets.  Each observation also streams to the attached sinks as a
    ``sample`` record -- timers sit on low-frequency paths (steps,
    compiles, collectives, batch waits), so per-observation streaming is
    affordable and gives the JSONL log per-step resolution."""

    kind = "timer"

    def __init__(self, name, registry=None):
        super().__init__(name, registry)
        self.reset()

    def observe(self, seconds, **fields):
        seconds = float(seconds)
        with self._lock:
            self._count += 1
            self._sum += seconds
            self._min = seconds if self._min is None \
                else min(self._min, seconds)
            self._max = seconds if self._max is None \
                else max(self._max, seconds)
            # first bucket whose upper bound holds the observation
            idx = min(bisect.bisect_left(_TIMER_BUCKETS, seconds),
                      len(_TIMER_BUCKETS) - 1)
            self._buckets[idx] += 1
        self._stream("sample", value=seconds, **fields)

    def time(self, **fields):
        """``with timer.time(): ...`` convenience."""
        return _TimerContext(self, fields)

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q):
        """Histogram-estimated q-quantile (0 < q <= 1): the upper bound
        of the bucket where the cumulative count crosses ``q * count``,
        clamped into [min, max] so single-observation timers report the
        observation itself rather than a bucket edge."""
        with self._lock:
            count = self._count
            if not count:
                return None
            rank = q * count
            acc = 0
            est = self._max
            for bound, n in zip(_TIMER_BUCKETS, self._buckets):
                acc += n
                if acc >= rank:
                    est = bound
                    break
            return min(max(est, self._min), self._max)

    def _percentiles(self):
        return {"p50": self.percentile(0.50),
                "p95": self.percentile(0.95),
                "p99": self.percentile(0.99)}

    def snapshot(self):
        return {"kind": "timer", "name": self.name, "count": self._count,
                "sum": self._sum, "min": self._min, "max": self._max,
                "mean": (self._sum / self._count) if self._count else None,
                **self._percentiles(),
                "buckets": {("%g" % b): n for b, n in
                            zip(_TIMER_BUCKETS, self._buckets) if n}}

    def reset(self):
        with self._lock:
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None
            self._buckets = [0] * len(_TIMER_BUCKETS)


class _TimerContext:
    __slots__ = ("_timer", "_fields", "_t0")

    def __init__(self, timer, fields):
        self._timer = timer
        self._fields = fields

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.observe(time.perf_counter() - self._t0, **self._fields)


class Event(Instrument):
    """Structured occurrences with a payload dict (retraces, AMP
    overflows, checkpoints).  Keeps a bounded ring of recent payloads
    and streams every emit to the sinks."""

    kind = "event"

    def __init__(self, name, registry=None):
        super().__init__(name, registry)
        self.reset()

    def emit(self, **payload):
        with self._lock:
            self._count += 1
            self._ring.append(payload)
            if len(self._ring) > _EVENT_RING:
                del self._ring[0]
        self._stream("event", payload=payload)

    @property
    def count(self):
        return self._count

    @property
    def recent(self):
        return list(self._ring)

    def snapshot(self):
        return {"kind": "event", "name": self.name, "count": self._count,
                "last_payload": self._ring[-1] if self._ring else None}

    def reset(self):
        with self._lock:
            self._count = 0
            self._ring = []


_KINDS = {"counter": Counter, "gauge": Gauge, "timer": Timer,
          "event": Event}


class Registry:
    """Thread-safe name -> instrument store with attached sinks.

    One process-global instance lives in ``telemetry/__init__.py``;
    tests may build private registries.  Sinks receive streamed records
    (event emits, timer samples) as they happen and the full snapshot at
    ``flush()``.
    """

    def __init__(self):
        self._lock = _sync.Lock(name="telemetry.registry")
        self._instruments = {}
        self._sinks = []

    # -- typed get-or-create ------------------------------------------
    def _get(self, cls, name):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name, registry=self)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise ValueError(
                "telemetry instrument %r already exists as %s, not %s"
                % (name, inst.kind, cls.kind))
        return inst

    def counter(self, name) -> Counter:
        return self._get(Counter, name)

    def gauge(self, name) -> Gauge:
        return self._get(Gauge, name)

    def timer(self, name) -> Timer:
        return self._get(Timer, name)

    def event(self, name) -> Event:
        return self._get(Event, name)

    def get(self, name):
        return self._instruments.get(name)

    def names(self):
        return sorted(self._instruments)

    # -- sinks ---------------------------------------------------------
    def attach(self, sink):
        with self._lock:
            self._sinks.append(sink)
        return sink

    def detach(self, sink):
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def _stream(self, record):
        for sink in self._sinks:
            write = getattr(sink, "write", None)
            if write is not None:
                write(record)

    # -- snapshot / lifecycle -----------------------------------------
    def snapshot(self):
        """List of per-instrument snapshot dicts, sorted by name."""
        with self._lock:
            insts = sorted(self._instruments.items())
        return [inst.snapshot() for _name, inst in insts]

    def flush(self):
        """Push the aggregate snapshot through every sink that keeps a
        file (JSONL) and flush it."""
        snap = self.snapshot()
        now = time.time()
        for rec in snap:
            self._stream({"t": now, **rec, "kind": "snapshot."
                          + rec["kind"]})
        for sink in list(self._sinks):
            fl = getattr(sink, "flush", None)
            if fl is not None:
                fl()

    def reset(self, prefix=None):
        """Zero every instrument (or only names under ``prefix``).
        Instruments stay registered so live references keep working."""
        with self._lock:
            insts = list(self._instruments.items())
        for name, inst in insts:
            if prefix is None or name.startswith(prefix):
                inst.reset()

    def clear(self, prefix=None):
        """Drop instruments entirely (tests)."""
        with self._lock:
            if prefix is None:
                self._instruments.clear()
            else:
                for name in [n for n in self._instruments
                             if n.startswith(prefix)]:
                    del self._instruments[name]
