"""``python -m mxnet_tpu.telemetry`` -- offline analysis of telemetry
JSONL run logs and flight-recorder black boxes.

Subcommands:

- ``summarize run.jsonl [more_rank_files...]`` -- aggregate one run log
  (steps, compiles, kvstore, feed, serving, spans); given SEVERAL rank
  files from one multi-host run, also emits per-rank step-time skew and
  a straggler flag (max/median mean-step wall past ``--skew-threshold``)
  -- the first skew instrument multi-host SPMD has.
- ``blackbox crash.bbox`` -- render a flight-recorder ring
  (``mx.obs.flight``): the final records before the process died.
- ``fleet <endpoints-dir | url...>`` -- scrape the live fleet
  (``mx.obs.fleet``): the per-replica table, pooled SLO aggregates,
  and the alert engine's firing/pending/history view.

Contract mirrors the mxlint CLI (``mxnet_tpu.analysis.cli``): exit 0 on
success with ``--json`` for machine-readable output, exit 1 when the log
is missing/empty (nothing to summarize is a failed gate in CI) or --
for ``fleet`` -- while ANY alert fires, exit 2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import sys

from .sinks import _fmt_secs, prom_text, summary_table

__all__ = ["main", "summarize_file", "summarize_files"]

# Exact-percentile bound: past this many streamed samples per timer the
# tail is dropped from the percentile pool (count/sum/min/max stay
# exact) -- an offline summarizer must not grow with run length.
_MAX_PCTL_SAMPLES = 200_000


def _exact_percentiles(values):
    """p50/p95/p99 (nearest-rank) from exact sample values."""
    if not values:
        return {}
    values = sorted(values)
    n = len(values)

    def rank(q):
        return values[min(n - 1, max(0, int(round(q * n)) - 1))]

    return {"p50": rank(0.50), "p95": rank(0.95), "p99": rank(0.99)}


def _build_parser():
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.telemetry",
        description="Summarize a telemetry JSONL run log "
                    "(docs/observability.md).")
    sub = ap.add_subparsers(dest="cmd")
    sm = sub.add_parser("summarize", help="aggregate run.jsonl file(s)")
    sm.add_argument("paths", nargs="+", metavar="path",
                    help="telemetry JSONL file(s) "
                         "(MXNET_TPU_TELEMETRY_JSONL); several files = "
                         "per-rank skew analysis")
    sm.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable aggregate")
    sm.add_argument("--prom", action="store_true",
                    help="Prometheus text exposition instead of the "
                         "console table (single file only)")
    sm.add_argument("--skew-threshold", type=float, default=1.25,
                    help="straggler flag threshold on max/median "
                         "mean-step wall across rank files "
                         "(default 1.25)")
    bb = sub.add_parser("blackbox",
                        help="render a flight-recorder ring "
                             "(mx.obs.flight / MXNET_TPU_OBS_BLACKBOX)")
    bb.add_argument("path", help="flight-recorder file")
    bb.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable record list")
    bb.add_argument("--last", type=int, default=40,
                    help="records to show in the human rendering "
                         "(default 40)")
    fl = sub.add_parser("fleet",
                        help="scrape and render the live fleet "
                             "(mx.obs.fleet / "
                             "MXNET_TPU_OBS_ENDPOINTS_DIR)")
    fl.add_argument("source", nargs="+", metavar="dir-or-url",
                    help="ONE endpoints directory, or one or more "
                         "http:// replica base URLs")
    fl.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable fleet snapshot + alerts")
    fl.add_argument("--rounds", type=int, default=2,
                    help="scrape rounds before rendering (>= 2 so "
                         "rate/ratio deltas exist; default 2)")
    fl.add_argument("--interval-ms", type=float, default=None,
                    help="inter-round interval (default "
                         "MXNET_TPU_OBS_SCRAPE_MS)")
    return ap


def summarize_file(path):
    """Aggregate one JSONL run log into a dict.

    Streamed ``event``/``sample`` records are folded per name; trailing
    ``snapshot.*`` records (written by ``telemetry.flush()``) win over
    the folds for the instruments they cover, since they carry the
    authoritative counts.  Returns the aggregate; raises OSError when
    the file cannot be read.
    """
    counters, gauges, timers, events = {}, {}, {}, {}
    sample_folds = {}
    event_folds = {}
    span_folds = {}
    records = skipped = 0
    rank = None
    goodput_active = None     # last goodput.window payload WITH steps
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                kind = rec["kind"]
                name = rec["name"]
            except (ValueError, KeyError, TypeError):
                skipped += 1
                continue
            records += 1
            if rank is None and isinstance(rec.get("rank"), int):
                rank = rec["rank"]
            if kind == "span":
                agg = span_folds.setdefault(
                    name, {"count": 0, "sum": 0.0, "min": None,
                           "max": None})
                d = float(rec.get("dur", 0.0))
                agg["count"] += 1
                agg["sum"] += d
                agg["min"] = d if agg["min"] is None \
                    else min(agg["min"], d)
                agg["max"] = d if agg["max"] is None \
                    else max(agg["max"], d)
            elif kind == "sample":
                agg = sample_folds.setdefault(
                    name, {"count": 0, "sum": 0.0, "min": None,
                           "max": None, "values": [], "t_first": None,
                           "t_last": None})
                v = float(rec.get("value", 0.0))
                agg["count"] += 1
                agg["sum"] += v
                agg["min"] = v if agg["min"] is None else min(agg["min"], v)
                agg["max"] = v if agg["max"] is None else max(agg["max"], v)
                if len(agg["values"]) < _MAX_PCTL_SAMPLES:
                    agg["values"].append(v)
                t = rec.get("t")
                if isinstance(t, (int, float)):
                    if agg["t_first"] is None:
                        agg["t_first"] = t
                    agg["t_last"] = t
            elif kind == "event":
                agg = event_folds.setdefault(
                    name, {"count": 0, "last_payload": None})
                agg["count"] += 1
                agg["last_payload"] = rec.get("payload")
                # the goodput verdict must come from the last ACTIVE
                # window -- a zero-step tail flush (trainer close,
                # serving-only lull) reads "idle" and must not mask it
                if name == "goodput.window" \
                        and isinstance(rec.get("payload"), dict) \
                        and rec["payload"].get("steps"):
                    goodput_active = rec["payload"]
            elif kind == "snapshot.counter":
                counters[name] = rec.get("value", 0)
            elif kind == "snapshot.gauge":
                if rec.get("value") is not None:
                    gauges[name] = {k: rec.get(k) for k in
                                    ("value", "count", "min", "max")}
            elif kind == "snapshot.timer":
                timers[name] = {k: rec.get(k) for k in
                                ("count", "sum", "min", "max", "mean",
                                 "p50", "p95", "p99")
                                if rec.get(k) is not None}
            elif kind == "snapshot.event":
                events[name] = {"count": rec.get("count", 0),
                                "last_payload": rec.get("last_payload")}
            else:
                skipped += 1
    # streamed folds fill in anything the final snapshot missed (e.g. a
    # run killed before flush) -- and, because they carry the exact
    # sample values, they upgrade every snapshot timer's
    # histogram-estimated percentiles to exact ones
    for name, agg in sample_folds.items():
        pctl = _exact_percentiles(agg.pop("values"))
        span = (agg.pop("t_last") or 0) - (agg.pop("t_first") or 0)
        rate = (agg["count"] - 1) / span \
            if span > 0 and agg["count"] > 1 else None
        if name not in timers:
            timers[name] = {**agg, "mean": (agg["sum"] / agg["count"])
                            if agg["count"] else None}
        timers[name].update(pctl)
        if rate is not None:
            timers[name]["rate_per_sec"] = round(rate, 2)
    for name, agg in event_folds.items():
        if name not in events:
            events[name] = agg

    step = timers.get("trainer.step_time", {})
    spsec = gauges.get("trainer.samples_per_sec", {})
    compile_ev = events.get("compile", {})
    result = {
        "file": path,
        "rank": rank,
        "records": records,
        "skipped": skipped,
        "spans": {name: {**agg,
                         "mean": (agg["sum"] / agg["count"])
                         if agg["count"] else None}
                  for name, agg in sorted(span_folds.items())},
        "counters": counters,
        "gauges": gauges,
        "timers": timers,
        "events": events,
        "steps": {
            "count": step.get("count", 0),
            "total_s": step.get("sum"),
            "mean_s": step.get("mean"),
            "samples": counters.get("trainer.samples", 0),
            "samples_per_sec": spsec.get("value"),
        },
        "compile": {
            "count": counters.get("compile.count",
                                  compile_ev.get("count", 0)),
            "retraces": counters.get("compile.retraces", 0),
            "build_time_s": timers.get("compile.build_time",
                                       {}).get("sum"),
            "last": compile_ev.get("last_payload"),
        },
        "kvstore": {
            "pushpull": counters.get("kvstore.pushpull", 0),
            "push": counters.get("kvstore.push", 0),
            "pull": counters.get("kvstore.pull", 0),
            "bytes": counters.get("kvstore.bytes", 0),
            "time_s": timers.get("kvstore.time", {}).get("sum"),
        },
        "data": {
            "batches": counters.get("data.batches", 0),
            "wait_s": timers.get("data.wait_time", {}).get("sum"),
            "mean_wait_s": timers.get("data.wait_time", {}).get("mean"),
        },
        "feed": {
            "batches": counters.get("feed.batches", 0),
            "bytes_staged": counters.get("feed.bytes_staged", 0),
            "producer_busy_s": timers.get("feed.producer_busy",
                                          {}).get("sum"),
            "consumer_wait_s": timers.get("feed.consumer_wait",
                                          {}).get("sum"),
            "overlap_frac": gauges.get("feed.overlap_frac",
                                       {}).get("value"),
        },
        "serving": _serving_section(counters, timers),
        "goodput": _goodput_section(counters, gauges, timers, events,
                                    goodput_active),
    }
    return result


# the ledger's category order (mirrors obs.goodput.CATEGORIES; literal
# here so offline summarize never imports the obs package)
_GOODPUT_CATEGORIES = ("device_compute", "input_wait", "host_sync",
                       "checkpoint_stall", "recompile", "other")


def _goodput_section(counters, gauges, timers, events,
                     last_active=None):
    """Rollup of the goodput.* instruments (obs.goodput StepLedger):
    per-category attributed seconds (timer sums -- exact across the
    whole run), the latest window's verdict, and the sentinel's
    regression/env-degraded tallies."""
    windows = counters.get("goodput.windows",
                           events.get("goodput.window",
                                      {}).get("count", 0))
    if not windows:
        return {"windows": 0}
    steps = counters.get("goodput.steps", 0)
    cats = {}
    total = 0.0
    for cat in _GOODPUT_CATEGORIES:
        s = timers.get("goodput.%s_s" % cat, {}).get("sum") or 0.0
        cats[cat] = {"total_s": round(s, 6)}
        total += s
    for cat in cats:
        cats[cat]["share"] = round(cats[cat]["total_s"] / total, 4) \
            if total > 0 else None
        cats[cat]["per_step_s"] = round(cats[cat]["total_s"] / steps, 6) \
            if steps else None
    last = last_active \
        or events.get("goodput.window", {}).get("last_payload") or {}
    return {
        "windows": windows,
        "steps": steps,
        "wall_s": round(total, 6),
        "categories": cats,
        "mfu": gauges.get("goodput.mfu", {}).get("value"),
        "verdict": last.get("verdict"),
        "bound": last.get("bound"),
        "reconciliation_error":
        gauges.get("goodput.reconciliation_error", {}).get("value"),
        "regressions": counters.get("goodput.regressions", 0),
        "last_regression": events.get("goodput.regression",
                                      {}).get("last_payload"),
        "env_degraded_windows":
        counters.get("goodput.env_degraded_windows", 0),
    }


def _serving_section(counters, timers):
    """SLO rollup of the serving.* instruments (docs/serving.md)."""
    requests = counters.get("serving.requests", 0)
    batches = counters.get("serving.batches", 0)
    responses = counters.get("serving.responses", 0)
    lat = timers.get("serving.latency", {})
    return {
        "requests": requests,
        "responses": responses,
        "batches": batches,
        "mean_occupancy": round(responses / batches, 3) if batches
        else None,
        "shed": counters.get("serving.shed", 0),
        "timeouts": counters.get("serving.timeouts", 0),
        "qps": lat.get("rate_per_sec"),
        "latency_p50_s": lat.get("p50"),
        "latency_p95_s": lat.get("p95"),
        "latency_p99_s": lat.get("p99"),
        "latency_mean_s": lat.get("mean"),
        "swaps": counters.get("serving.swaps", 0),
        "swap_failures": counters.get("serving.swap_failures", 0),
        "compile_cache_hits": counters.get("serving.compile_cache_hits",
                                           0),
        "compile_cache_misses":
        counters.get("serving.compile_cache_misses", 0),
        "compile_evictions": counters.get("serving.compile_evictions", 0),
    }


def summarize_files(paths, skew_threshold=1.25):
    """Aggregate SEVERAL rank files from one multi-host run: per-rank
    step statistics plus the skew verdict (straggler flag when the
    slowest rank's mean step wall exceeds ``skew_threshold`` x the
    median) -- GSPMD steps are lockstep, so a straggler rank drags
    every rank's wall; this names it."""
    per_rank = []
    records = 0
    for i, path in enumerate(paths):
        agg = summarize_file(path)
        records += agg["records"]
        st = agg["steps"]
        rank = agg["rank"] if agg["rank"] is not None else i
        gp = agg.get("goodput") or {}
        per_rank.append({
            "file": path,
            "rank": rank,
            "records": agg["records"],
            "steps": st["count"],
            "mean_step_s": st["mean_s"],
            "total_step_s": st["total_s"],
            "samples_per_sec": st["samples_per_sec"],
            # per-step goodput category seconds (None without a ledger)
            "goodput": {cat: c["per_step_s"]
                        for cat, c in gp.get("categories", {}).items()}
            if gp.get("windows") else None,
        })
    means = sorted(r["mean_step_s"] for r in per_rank
                   if r["mean_step_s"])
    skew = None
    stragglers = []
    if means:
        # lower-middle for even counts: with 2 ranks the healthy one is
        # the reference, so a straggler pair reads as skewed, not 1.0
        median = means[(len(means) - 1) // 2]
        worst = means[-1]
        skew = (worst / median) if median else None
        if skew is not None:
            stragglers = sorted(
                r["rank"] for r in per_rank
                if r["mean_step_s"]
                and median
                and r["mean_step_s"] / median > skew_threshold)
    return {
        "files": list(paths),
        "records": records,
        "ranks": per_rank,
        "skew": {
            "max_over_median": round(skew, 4) if skew else None,
            "threshold": skew_threshold,
            "straggler": bool(stragglers),
            "straggler_ranks": stragglers,
            # ISSUE 14 satellite: name WHICH goodput category differs
            # on the slow rank, not just that it is slow
            "category_attribution": _straggler_categories(per_rank,
                                                          stragglers),
        },
    }


def _straggler_categories(per_rank, stragglers):
    """For each straggler rank, the goodput category whose per-step
    seconds deviate most from the cross-rank median -- e.g. "rank 2
    input_wait 3.1x median".  Empty when no rank carries ledger data
    (the skew verdict itself still works from step timers alone)."""
    ranks_with = [r for r in per_rank if r.get("goodput")]
    if not stragglers or len(ranks_with) < 2:
        return []
    medians = {}
    for cat in _GOODPUT_CATEGORIES:
        vals = sorted(r["goodput"].get(cat) or 0.0 for r in ranks_with)
        medians[cat] = vals[(len(vals) - 1) // 2]
    out = []
    for r in ranks_with:
        if r["rank"] not in stragglers:
            continue
        best = None
        for cat in _GOODPUT_CATEGORIES:
            if cat == "other":
                continue
            v = r["goodput"].get(cat) or 0.0
            ratio = v / max(medians[cat], 1e-9)
            if v > medians[cat] and (best is None
                                     or ratio > best["ratio"]):
                best = {"rank": r["rank"], "category": cat,
                        "per_step_s": round(v, 6),
                        "median_per_step_s": round(medians[cat], 6),
                        "ratio": round(min(ratio, 999.0), 2)}
        if best is not None:
            out.append(best)
    return out


def _render_ranks(agg):
    lines = ["telemetry rank summary: %d files (%d records)"
             % (len(agg["files"]), agg["records"]), "",
             "  %-6s %-8s %-12s %-12s %s"
             % ("rank", "steps", "mean step", "total", "file"),
             "  " + "-" * 68]
    for r in agg["ranks"]:
        lines.append("  %-6s %-8d %-12s %-12s %s"
                     % (r["rank"], r["steps"],
                        _fmt_secs(r["mean_step_s"]),
                        _fmt_secs(r["total_step_s"]), r["file"]))
    sk = agg["skew"]
    if sk["max_over_median"] is not None:
        lines.append("")
        lines.append(
            "  step-time skew max/median = %.3f (threshold %.2f): %s"
            % (sk["max_over_median"], sk["threshold"],
               "STRAGGLER rank(s) %s" % sk["straggler_ranks"]
               if sk["straggler"] else "balanced"))
        for attr in sk.get("category_attribution") or ():
            lines.append(
                "  rank %s slow: %s %.1fx median "
                "(%.1fms vs %.1fms per step)"
                % (attr["rank"], attr["category"], attr["ratio"],
                   1e3 * attr["per_step_s"],
                   1e3 * attr["median_per_step_s"]))
    return "\n".join(lines)


def _render_blackbox(records, path, last):
    t_end = max((r.get("t") for r in records
                 if isinstance(r.get("t"), (int, float))),
                default=None)
    shown = records[-last:] if last and last > 0 else records
    lines = ["blackbox: %s (%d records, showing last %d)"
             % (path, len(records), len(shown))]
    for r in shown:
        t = r.get("t")
        rel = ("%+.3fs" % (t - t_end)) \
            if t_end is not None and isinstance(t, (int, float)) \
            else "?"
        kind = r.get("kind", "?")
        name = r.get("name", "?")
        if kind == "span":
            detail = "dur=%s trace=%s" % (_fmt_secs(r.get("dur")),
                                          r.get("trace"))
        elif kind == "event":
            detail = json.dumps(r.get("payload"), default=str)[:120]
        elif kind == "sample":
            detail = "value=%s" % _fmt_secs(r.get("value"))
        else:
            detail = json.dumps({k: v for k, v in r.items()
                                 if k not in ("kind", "name", "t")},
                                default=str)[:120]
        lines.append("  %-10s %-8s %-34s %s" % (rel, kind, name,
                                                detail))
    return "\n".join(lines)


def _to_snapshot(agg):
    """Rebuild a Registry.snapshot()-shaped list from an aggregate so
    the offline CLI reuses the live renderers."""
    snap = []
    for name, value in sorted(agg["counters"].items()):
        snap.append({"kind": "counter", "name": name, "value": value})
    for name, g in sorted(agg["gauges"].items()):
        snap.append({"kind": "gauge", "name": name, **g})
    for name, t in sorted(agg["timers"].items()):
        snap.append({"kind": "timer", "name": name, "buckets": {}, **t})
    for name, e in sorted(agg["events"].items()):
        snap.append({"kind": "event", "name": name, **e})
    return snap


def _render_human(agg):
    lines = ["telemetry summary: %s (%d records)"
             % (agg["file"], agg["records"]), ""]
    st = agg["steps"]
    if st["count"]:
        sps = st["samples_per_sec"]
        lines.append(
            "  steps: %d in %.3fs (mean %.1fms)%s"
            % (st["count"], st["total_s"] or 0.0,
               1e3 * (st["mean_s"] or 0.0),
               ", %.1f samples/sec" % sps if sps else ""))
    cp = agg["compile"]
    if cp["count"]:
        lines.append("  compiles: %d (%d retraces, %.3fs building)"
                     % (cp["count"], cp["retraces"],
                        cp["build_time_s"] or 0.0))
    kv = agg["kvstore"]
    if kv["pushpull"] or kv["push"] or kv["pull"]:
        lines.append("  kvstore: %d pushpull / %d push / %d pull, "
                     "%d bytes" % (kv["pushpull"], kv["push"],
                                   kv["pull"], kv["bytes"]))
    da = agg["data"]
    if da["batches"]:
        lines.append("  input: %d batches, %.3fs waiting (mean %.1fms)"
                     % (da["batches"], da["wait_s"] or 0.0,
                        1e3 * (da["mean_wait_s"] or 0.0)))
    sv = agg.get("serving", {})
    if sv.get("requests"):
        occ = sv.get("mean_occupancy")
        lat = [("p%s" % p, sv.get("latency_p%s_s" % p))
               for p in (50, 95, 99)]
        lat_txt = " ".join("%s=%.1fms" % (k, 1e3 * v)
                           for k, v in lat if v is not None)
        lines.append(
            "  serving: %d requests in %d batches%s, %d shed / %d "
            "timed out%s%s"
            % (sv["requests"], sv["batches"],
               " (occupancy %.2f)" % occ if occ is not None else "",
               sv["shed"], sv["timeouts"],
               ", %.1f qps" % sv["qps"] if sv.get("qps") else "",
               (", " + lat_txt) if lat_txt else ""))
    fd = agg.get("feed", {})
    if fd.get("batches"):
        lines.append(
            "  feed: %d batches, %d bytes staged, %.3fs producing / "
            "%.3fs waiting%s"
            % (fd["batches"], fd["bytes_staged"],
               fd["producer_busy_s"] or 0.0, fd["consumer_wait_s"] or 0.0,
               ", overlap %.1f%%" % (100 * fd["overlap_frac"])
               if fd.get("overlap_frac") is not None else ""))
    gp = agg.get("goodput") or {}
    if gp.get("windows"):
        shares = ", ".join(
            "%s %.0f%%" % (cat, 100 * gp["categories"][cat]["share"])
            for cat in _GOODPUT_CATEGORIES
            if gp["categories"][cat]["share"])
        lines.append(
            "  goodput: %d windows / %d steps%s%s%s"
            % (gp["windows"], gp["steps"],
               " (%s)" % shares if shares else "",
               ", mfu %.3f" % gp["mfu"] if gp.get("mfu") is not None
               else "",
               ", %d regressions" % gp["regressions"]
               if gp.get("regressions") else ""))
        if gp.get("verdict"):
            # THE bottleneck verdict line, e.g. "input-bound: feed
            # supplies 54% of device demand"
            lines.append("  bottleneck: %s%s"
                         % (gp["verdict"],
                            " [env degraded: %d windows]"
                            % gp["env_degraded_windows"]
                            if gp.get("env_degraded_windows") else ""))
    spn = agg.get("spans") or {}
    if spn:
        lines.append("  spans: %d recorded over %d names (top: %s)"
                     % (sum(v["count"] for v in spn.values()), len(spn),
                        ", ".join(sorted(
                            spn, key=lambda n: -spn[n]["count"])[:4])))
    lines.append("")
    lines.append(summary_table(_to_snapshot(agg)))
    return "\n".join(lines)


def _main_blackbox(args):
    from ..obs import flight
    from ..base import MXNetError
    try:
        records = flight.read(args.path)
    except OSError as e:
        print("cannot read %s: %s" % (args.path, e), file=sys.stderr)
        return 1
    except MXNetError as e:
        print(str(e), file=sys.stderr)
        return 1
    if not records:
        print("no records in %s" % args.path, file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(records, indent=2, default=str))
    else:
        print(_render_blackbox(records, args.path, args.last))
    return 0


def _main_fleet(args):
    """``mxtelemetry fleet``: poll the fleet ``--rounds`` times and
    render the table + alerts.  Exit 0 healthy, 1 while ANY alert
    fires (the pageable condition -- same contract as the mxlint
    gate) or when nothing was scrapeable, 2 on usage errors."""
    import os as _os
    import time as _time
    from ..obs.fleet import FleetMonitor
    dirs = [s for s in args.source if not s.startswith("http")]
    urls = [s for s in args.source if s.startswith("http")]
    if dirs and urls:
        print("fleet: mixing an endpoints dir and URLs is ambiguous; "
              "pass one or the other", file=sys.stderr)
        return 2
    if len(dirs) > 1:
        print("fleet: exactly one endpoints directory", file=sys.stderr)
        return 2
    if dirs and not _os.path.isdir(dirs[0]):
        print("fleet: %s is not a directory" % dirs[0], file=sys.stderr)
        return 2
    mon = FleetMonitor(dirs[0] if dirs else urls,
                       scrape_ms=args.interval_ms)
    try:
        rounds = max(int(args.rounds), 1)
        for i in range(rounds):
            if i:
                _time.sleep(mon.scrape_s)
            snap = mon.poll_once()
        if args.as_json:
            print(json.dumps({"fleet": snap,
                              "alerts": mon.engine.alertz()},
                             indent=2, sort_keys=True, default=str))
        else:
            print(mon.table())
        if mon.engine.firing():
            return 1
        if not any(r["state"] in ("ok", "init")
                   for r in snap["replicas"]):
            print("fleet: no scrapeable replica in %s"
                  % " ".join(args.source), file=sys.stderr)
            return 1
        return 0
    finally:
        mon.close()


def main(argv=None) -> int:
    ap = _build_parser()
    args = ap.parse_args(argv)
    if args.cmd == "blackbox":
        return _main_blackbox(args)
    if args.cmd == "fleet":
        return _main_fleet(args)
    if args.cmd != "summarize":
        ap.print_usage()
        return 2
    multi = len(args.paths) > 1
    try:
        agg = summarize_files(args.paths, args.skew_threshold) \
            if multi else summarize_file(args.paths[0])
    except OSError as e:
        print("cannot read: %s" % e, file=sys.stderr)
        return 1
    if not agg["records"]:
        print("no telemetry records in %s" % " ".join(args.paths),
              file=sys.stderr)
        return 1
    try:
        if args.as_json:
            print(json.dumps(agg, indent=2, sort_keys=True))
        elif multi:
            print(_render_ranks(agg))
        elif args.prom:
            print(prom_text(_to_snapshot(agg)), end="")
        else:
            print(_render_human(agg))
    except BrokenPipeError:
        # downstream pager/head closed early: that's a success, not a
        # stack trace.  Point stdout at devnull so interpreter teardown
        # doesn't re-raise on the final flush.
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
