"""Always-on runtime telemetry (ISSUE 2 tentpole).

``mx.profiler`` delegates tracing to ``jax.profiler`` -- a TensorBoard
trace you load after the fact.  This subsystem is the complementary
production layer: typed Counters/Gauges/Timers/Events over the
framework's hot paths (imperative dispatch, compile caches, trainer
steps, kvstore traffic, input pipeline, AMP, preemption), cheap enough
to leave enabled for a whole run and queryable as data.

Enable with ``MXNET_TPU_TELEMETRY=1`` in the environment or
``mx.telemetry.enable()`` in code.  When disabled (the default), every
instrumented hot path pays exactly ONE module-attribute flag check
(``telemetry._ENABLED``) and makes zero instrument calls -- proven by
tests/test_telemetry.py::test_disabled_mode_makes_zero_instrument_calls.

Sinks: a JSONL run log (``MXNET_TPU_TELEMETRY_JSONL=/path`` or
``attach_jsonl(path)``), Prometheus text exposition (``prom_dump()``),
and a console summary table (``summary()``).  Offline analysis:
``python -m mxnet_tpu.telemetry summarize run.jsonl [--json | --prom]``.
"""
from __future__ import annotations

import atexit
import os

from .core import Counter, Event, Gauge, Registry, Timer
from .sinks import JsonlSink, prom_text, summary_table

__all__ = [
    "enable", "disable", "enabled", "reset", "flush",
    "counter", "gauge", "timer", "event", "registry",
    "attach_jsonl", "prom_dump", "summary",
    "Counter", "Gauge", "Timer", "Event", "Registry", "JsonlSink",
]

# THE flag every hot-path hook checks (one module-attribute read).
# Mutate only through enable()/disable() so the env-var view, the
# runtime.Features row, and the hooks stay coherent.
_ENABLED = False

_registry = Registry()
_jsonl_sink = None
_atexit_armed = False

from . import hooks  # noqa: E402  (needs _registry defined above)


def enable():
    """Turn the hot-path hooks on (idempotent)."""
    global _ENABLED
    _ENABLED = True


def disable():
    """Turn the hot-path hooks off; instruments keep their values."""
    global _ENABLED
    _ENABLED = False


def enabled():
    return _ENABLED


def registry() -> Registry:
    return _registry


def counter(name) -> Counter:
    return _registry.counter(name)


def gauge(name) -> Gauge:
    return _registry.gauge(name)


def timer(name) -> Timer:
    return _registry.timer(name)


def event(name) -> Event:
    return _registry.event(name)


def reset(prefix=None):
    """Zero all instruments (or only names under ``prefix``)."""
    _registry.reset(prefix)


def flush():
    """Append the aggregate snapshot to attached sinks and flush them."""
    _registry.flush()


def attach_jsonl(path):
    """Attach (or replace) the JSONL run-log sink; returns the sink.
    The snapshot is flushed to it at interpreter exit."""
    global _jsonl_sink, _atexit_armed
    if _jsonl_sink is not None:
        _registry.detach(_jsonl_sink)
        _jsonl_sink.close()
    _jsonl_sink = _registry.attach(JsonlSink(path))
    if not _atexit_armed:
        atexit.register(_atexit_flush)
        _atexit_armed = True
    return _jsonl_sink


def _atexit_flush():
    if _jsonl_sink is not None:
        try:
            _registry.flush()
        except Exception:
            pass


def prom_dump(path=None):
    """Prometheus text exposition of the current snapshot; written to
    ``path`` when given, returned either way."""
    text = prom_text(_registry.snapshot())
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def summary():
    """Human console summary table of the current snapshot."""
    return summary_table(_registry.snapshot())


# env arming (read directly, matching the package's != "0" convention;
# the typed registry view lives in mxnet_tpu/env.py)
if os.environ.get("MXNET_TPU_TELEMETRY", "0") != "0":
    enable()
_env_jsonl = os.environ.get("MXNET_TPU_TELEMETRY_JSONL", "")
if _env_jsonl:
    attach_jsonl(_env_jsonl)
