"""``python -m mxnet_tpu.telemetry`` -> the telemetry CLI."""
import sys

from .cli import main

sys.exit(main())
