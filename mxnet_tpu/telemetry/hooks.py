"""Hot-path recording helpers.

Every instrumented framework module (ndarray dispatch, executor, gluon
block/trainer, kvstore, dataloader, amp, preemption, callback) guards a
single call into this module with the one module-level flag check::

    if _telemetry._ENABLED:
        _telemetry.hooks.op_dispatch(op.name)

Keeping the recording logic here (instead of inline at each hook point)
means the hot modules carry exactly one branch when telemetry is off --
the zero-overhead contract tests/test_telemetry.py proves by counting
calls into this module -- and the instrument naming stays in one place.

Instrument naming (see docs/observability.md):

=====================  ======  =========================================
name                   kind    meaning
=====================  ======  =========================================
dispatch.op_calls      counter imperative op invocations (total)
dispatch.op.<op>       counter per-op invocation count
dispatch.host_sync     counter host sync points (asnumpy/wait/waitall)
dispatch.host_sync.<k> counter per-kind sync count
compile                event   one per XLA trace/compile, payload says
                               where and why (cache-key diff on retrace)
compile.count          counter total compiles
compile.retraces       counter compiles that REPLACED warm cache state
compile.build_time     timer   wall time spent tracing/compiling
trainer.step_time      timer   Trainer.step wall time
trainer.steps          counter optimizer steps taken
trainer.samples        counter samples pushed through step()
trainer.samples_per_sec gauge  throughput (Trainer.step + Speedometer)
kvstore.push/pull/
  pushpull             counter kvstore calls by verb
kvstore.bytes          counter gradient bytes moved through kvstore
kvstore.time           timer   wall time in pushpull (dispatch side)
data.batches           counter batches produced by DataLoader
data.wait_time         timer   consumer wait per batch (input
                               starvation when this rivals step_time)
feed.batches           counter batches staged by dataio.DeviceFeed
feed.bytes_staged      counter bytes shipped host->device by the feed
feed.producer_busy     timer   per-batch producer time (host batch +
                               async device_put issue)
feed.consumer_wait     timer   per-batch consumer wait on the staging
                               queue (transfer not hidden when this
                               rivals producer_busy)
feed.overlap_frac      gauge   per-epoch share of producer time hidden
                               behind compute: 1 - wait/busy
amp.overflow           event   fp16 grad overflow (scale halved)
amp.overflows          counter total overflow steps
amp.rescale            event   loss-scale growth after a clean window
amp.loss_scale         gauge   current loss scale
checkpoint             event   checkpoint save/restore (preemption
                               handler + CheckpointManager), payload
                               carries step/bytes/duration
checkpoint.saves       counter saves (incl. provisional)
checkpoint.restores    counter restores (preemption resume + manager)
checkpoint.bytes_written counter bytes committed by saves
checkpoint.bytes_read  counter bytes loaded by restores
checkpoint.save_time   timer   wall time serializing+committing a save
checkpoint.restore_time timer  wall time verifying+loading a restore
checkpoint.async_wait  timer   time a save spent draining the previous
                               in-flight async write (rivals step time
                               => saving faster than the I/O)
sync.contention_wait   timer   time spent blocked acquiring a
                               contended lock (MXNET_TPU_TSAN=1 only;
                               labeled by lock role name)
sync.hold_time         timer   lock hold duration (TSAN only)
sync.watchdog_fires    counter deadlock-watchdog expiries (TSAN only)
sync.inversions        counter lock-order inversions observed (TSAN
                               report-only mode records instead of
                               raising)
profiling.reports      counter CostReports materialized by the
                               mx.profiling store
profiling.capture_time timer   wall time lowering/parsing one report
profiling.capture      event   one per report, payload carries
                               label + FLOPs
profiling.step_time    timer   per-dispatch step wall recorded by
                               TrainStep under MXNET_TPU_PROFILING=1
                               (feeds the roofline)
serving.requests       counter requests accepted by serving submit()
serving.responses      counter responses scattered from dispatched
                               batches (mean batch occupancy =
                               responses / batches)
serving.batches        counter compiled batch dispatches
serving.batch_occupancy gauge  requests in the last dispatched batch
                               (>1 = dynamic batching is working)
serving.queue_depth    gauge   request-queue depth at last submit
serving.shed           counter submits rejected by a full queue
                               (ServingQueueFull backpressure)
serving.timeouts       counter requests expired while queued
                               (RequestTimeout)
serving.latency        timer   per-request round trip submit ->
                               response (the SLO metric; p50/p95/p99
                               in the summarize CLI)
serving.dispatch_time  timer   compiled-call wall per batch
serving.warmup_time    timer   per-servable registration warm-up
                               (all buckets compiled + executed)
serving.models         counter servables registered
serving.compile_cache_hits
                       counter bucket executables served from the
                               persistent serving compile cache
serving.compile_cache_misses
                       counter bucket executables compiled fresh (and
                               committed to the cache)
serving.compile_evictions
                       counter Predictor per-shape jit programs
                               evicted by the LRU bound
serving.swaps          counter successful hot-swaps (RegistryWatcher
                               re-register to a newer verified step)
serving.swap_failures  counter swap attempts that aborted (previous
                               servable kept serving)
serving.swap_time      timer   wall per successful swap (restore +
                               warm-up + install + old-servable drain)
serving.served_step    gauge   checkpoint step the live servable was
                               loaded from
train_loop.publishes   counter checkpoints published by
                               ContinuousTrainer
train_loop.published_step
                       gauge   newest step the trainer published
checkpoint.quarantined counter verification-failed steps renamed to
                               step_<N>.corrupt during discovery (each
                               is a rollback an operator should see)
checkpoint.write_retries
                       counter async-writer attempts retried after a
                               transient failure (exp backoff)
checkpoint.write_failures
                       counter async writes that failed EVERY attempt
                               (error also re-raises at next save/wait)
preemption.reentrant_signals
                       counter re-entrant SIGTERM deliveries suppressed
                               while a save was mid-commit
chaos.injected         counter faults injected by armed fail points
                               (chaos.injected.<point> per point)
chaos.survived         counter faults tolerated by a recovery path --
                               quarantine, write retry, swap rollback,
                               re-entrant-signal suppression
                               (chaos.survived.<point> per point)
=====================  ======  =========================================
"""
from __future__ import annotations

__all__ = [
    "op_dispatch", "host_sync", "compile_event", "trainer_step",
    "samples_per_sec", "kv_op", "dataloader_wait", "feed_produce",
    "feed_wait", "feed_overlap", "amp_overflow", "amp_rescale",
    "checkpoint", "checkpoint_wait",
    "sync_contention", "sync_hold", "sync_watchdog", "sync_inversion",
    "profiling_capture", "profiling_step",
    "serving_request", "serving_shed", "serving_timeout",
    "serving_batch", "serving_latency", "serving_warmup",
    "serving_model", "serving_compile_cache", "serving_evict",
    "serving_swap", "train_publish", "checkpoint_quarantine",
    "checkpoint_retry", "checkpoint_write_failed",
    "preemption_reentry", "chaos_inject", "chaos_survive",
]


def _registry():
    # late import: telemetry/__init__ rebinds the module-global registry
    # on reset; resolving through the package keeps hooks working
    from . import _registry
    return _registry


def op_dispatch(opname):
    reg = _registry()
    reg.counter("dispatch.op_calls").inc()
    reg.counter("dispatch.op." + opname).inc()


def host_sync(kind):
    reg = _registry()
    reg.counter("dispatch.host_sync").inc()
    reg.counter("dispatch.host_sync." + kind).inc()


def compile_event(site, seconds=None, retrace=False, **payload):
    """One XLA trace/compile happened at ``site`` (``hybrid_cache``,
    ``executor.train``, ``executor.eval``, ``eager_jit``).  ``retrace``
    marks a compile that joined a non-empty cache -- the runtime analog
    of the static retrace auditor's findings; ``payload`` carries the
    cache-key diff."""
    reg = _registry()
    reg.counter("compile.count").inc()
    if retrace:
        reg.counter("compile.retraces").inc()
    if seconds is not None:
        reg.timer("compile.build_time").observe(seconds, site=site)
    reg.event("compile").emit(site=site, retrace=bool(retrace),
                              seconds=seconds, **payload)


def trainer_step(seconds, batch_size):
    reg = _registry()
    reg.timer("trainer.step_time").observe(seconds)
    reg.counter("trainer.steps").inc()
    if batch_size:
        reg.counter("trainer.samples").inc(int(batch_size))
        if seconds > 0:
            reg.gauge("trainer.samples_per_sec").set(batch_size / seconds)


def samples_per_sec(value):
    """Throughput reported by an outer logger (callback.Speedometer):
    same gauge the Trainer feeds, so Module-API and Gluon training
    report through one channel."""
    _registry().gauge("trainer.samples_per_sec").set(value)


def kv_op(verb, nbytes, seconds=None):
    reg = _registry()
    reg.counter("kvstore." + verb).inc()
    if nbytes:
        reg.counter("kvstore.bytes").inc(int(nbytes))
    if seconds is not None:
        reg.timer("kvstore.time").observe(seconds, verb=verb)


def dist_collective(kind, nbytes, ntensors=1):
    """One host-side cross-process collective (distributed.py).  The
    hot training path moves ZERO bytes through here (gradients reduce
    in-graph, docs/distributed.md); what remains is init-time broadcast
    and metric/overflow reduction, and the bucketed wrappers coalesce
    N tensors into one call -- ``dist.collectives`` vs
    ``dist.tensors_coalesced`` is the call-count-drop proof."""
    reg = _registry()
    reg.counter("dist.collectives").inc()
    reg.counter("dist." + kind).inc()
    if nbytes:
        reg.counter("dist.bytes").inc(int(nbytes))
    if ntensors:
        reg.counter("dist.tensors_coalesced").inc(int(ntensors))


def dataloader_wait(seconds):
    reg = _registry()
    reg.counter("data.batches").inc()
    reg.timer("data.wait_time").observe(seconds)


def feed_produce(seconds, nbytes):
    reg = _registry()
    reg.counter("feed.batches").inc()
    if nbytes:
        reg.counter("feed.bytes_staged").inc(int(nbytes))
    reg.timer("feed.producer_busy").observe(seconds)


def feed_wait(seconds):
    _registry().timer("feed.consumer_wait").observe(seconds)


def feed_overlap(frac):
    _registry().gauge("feed.overlap_frac").set(frac)


def amp_overflow(scale_before, scale_after):
    reg = _registry()
    reg.counter("amp.overflows").inc()
    reg.gauge("amp.loss_scale").set(scale_after)
    reg.event("amp.overflow").emit(scale_before=scale_before,
                                   scale_after=scale_after)


def amp_rescale(scale_before, scale_after):
    reg = _registry()
    reg.gauge("amp.loss_scale").set(scale_after)
    reg.event("amp.rescale").emit(scale_before=scale_before,
                                  scale_after=scale_after)


def checkpoint(action, nbytes=None, seconds=None, **payload):
    reg = _registry()
    reg.counter("checkpoint.%ss" % action).inc()
    if nbytes:
        reg.counter("checkpoint.bytes_read" if action == "restore"
                    else "checkpoint.bytes_written").inc(int(nbytes))
    if seconds is not None:
        reg.timer("checkpoint.%s_time" % action).observe(seconds)
    reg.event("checkpoint").emit(action=action, nbytes=nbytes,
                                 seconds=seconds, **payload)


def checkpoint_wait(seconds, step=None):
    reg = _registry()
    reg.timer("checkpoint.async_wait").observe(
        seconds, **({} if step is None else {"step": step}))


def sync_contention(lock_name, seconds):
    _registry().timer("sync.contention_wait").observe(seconds,
                                                      lock=lock_name)


def sync_hold(lock_name, seconds):
    _registry().timer("sync.hold_time").observe(seconds, lock=lock_name)


def sync_watchdog(lock_name):
    reg = _registry()
    reg.counter("sync.watchdog_fires").inc()
    reg.event("sync.watchdog").emit(lock=lock_name)


def sync_inversion(outer, inner):
    reg = _registry()
    reg.counter("sync.inversions").inc()
    reg.event("sync.inversion").emit(outer=outer, inner=inner)


def profiling_capture(label, seconds, flops=None):
    """One CostReport was materialized by the mx.profiling store."""
    reg = _registry()
    reg.counter("profiling.reports").inc()
    reg.timer("profiling.capture_time").observe(seconds, label=label)
    reg.event("profiling.capture").emit(label=label, seconds=seconds,
                                        flops=flops)


def profiling_step(label, seconds):
    """One step wall time recorded for the roofline clock."""
    _registry().timer("profiling.step_time").observe(seconds,
                                                     label=label)


def serving_request(model, queue_depth):
    reg = _registry()
    reg.counter("serving.requests").inc()
    reg.gauge("serving.queue_depth").set(queue_depth)


def serving_shed(model):
    _registry().counter("serving.shed").inc()


def serving_timeout(model):
    _registry().counter("serving.timeouts").inc()


def serving_batch(model, occupancy, bucket, seconds):
    """One compiled batch dispatched: ``occupancy`` real requests
    padded to ``bucket``."""
    reg = _registry()
    reg.counter("serving.batches").inc()
    reg.counter("serving.responses").inc(int(occupancy))
    reg.gauge("serving.batch_occupancy").set(occupancy)
    reg.timer("serving.dispatch_time").observe(seconds, model=model,
                                               bucket=bucket,
                                               occupancy=occupancy)


def serving_latency(seconds):
    _registry().timer("serving.latency").observe(seconds)


def serving_warmup(model, seconds, n_buckets):
    _registry().timer("serving.warmup_time").observe(
        seconds, model=model, buckets=n_buckets)


def serving_model(model, source, n_buckets):
    reg = _registry()
    reg.counter("serving.models").inc()
    reg.event("serving.register").emit(model=model, source=source,
                                       buckets=n_buckets)


def serving_compile_cache(hit):
    _registry().counter("serving.compile_cache_hits" if hit
                        else "serving.compile_cache_misses").inc()


def serving_evict():
    _registry().counter("serving.compile_evictions").inc()


def serving_swap(model, step, seconds, ok, from_step=None, attempt=1,
                 error=None):
    """One hot-swap attempt by a RegistryWatcher finished."""
    reg = _registry()
    if ok:
        reg.counter("serving.swaps").inc()
        reg.timer("serving.swap_time").observe(seconds, model=model,
                                               step=step)
        reg.gauge("serving.served_step").set(step)
    else:
        reg.counter("serving.swap_failures").inc()
    reg.event("serving.swap").emit(model=model, step=step, ok=bool(ok),
                                   from_step=from_step, attempt=attempt,
                                   seconds=seconds, error=error)


def train_publish(step, seconds):
    """ContinuousTrainer published a checkpoint for the watcher."""
    reg = _registry()
    reg.counter("train_loop.publishes").inc()
    reg.gauge("train_loop.published_step").set(step)
    reg.event("train_loop.publish").emit(step=step, seconds=seconds)


def checkpoint_quarantine(step, path):
    """Discovery renamed a verification-failed step to .corrupt."""
    reg = _registry()
    reg.counter("checkpoint.quarantined").inc()
    reg.event("checkpoint.quarantine").emit(step=step, path=path)


def checkpoint_retry(attempt, error, step=None):
    """The async writer retried a failed background write."""
    reg = _registry()
    reg.counter("checkpoint.write_retries").inc()
    reg.event("checkpoint.write_retry").emit(attempt=attempt,
                                             error=error, step=step)


def checkpoint_write_failed(attempts, error, step=None):
    """An async write failed every attempt (error re-raises at the
    next save/wait; this event is the operator-visible surface)."""
    reg = _registry()
    reg.counter("checkpoint.write_failures").inc()
    reg.event("checkpoint.write_failed").emit(attempts=attempts,
                                              error=error, step=step)


def preemption_reentry():
    _registry().counter("preemption.reentrant_signals").inc()


def chaos_inject(point, action):
    """An armed fail point fired."""
    reg = _registry()
    reg.counter("chaos.injected").inc()
    reg.counter("chaos.injected." + point).inc()
    reg.event("chaos.inject").emit(point=point, action=action)


def chaos_survive(point, how):
    """A recovery path tolerated a fault (injected or real)."""
    reg = _registry()
    reg.counter("chaos.survived").inc()
    reg.counter("chaos.survived." + point).inc()
    reg.event("chaos.survive").emit(point=point, how=how)
