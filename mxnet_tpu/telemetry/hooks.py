"""Hot-path recording helpers.

Every instrumented framework module (ndarray dispatch, executor, gluon
block/trainer, kvstore, dataloader, amp, preemption, callback) guards a
single call into this module with the one module-level flag check::

    if _telemetry._ENABLED:
        _telemetry.hooks.op_dispatch(op.name)

Keeping the recording logic here (instead of inline at each hook point)
means the hot modules carry exactly one branch when telemetry is off --
the zero-overhead contract tests/test_telemetry.py proves by counting
calls into this module -- and the instrument naming stays in one place.

The instrument catalogue is DATA, not prose: :data:`INSTRUMENTS` below
is the single source of truth, and the index table in
``docs/observability.md`` is generated from it
(:func:`update_observability_doc`, the same cannot-go-stale contract as
``docs/env_vars.md``).  tests/test_obs.py cross-checks every literal
instrument name used in this module against the catalogue, so adding a
hook without cataloguing it fails CI.
"""
from __future__ import annotations

__all__ = [
    "op_dispatch", "host_sync", "compile_event", "trainer_step",
    "samples_per_sec", "kv_op", "dataloader_wait", "feed_produce",
    "feed_wait", "feed_overlap", "amp_overflow", "amp_rescale",
    "numerics_check", "numerics_nonfinite",
    "memory_census", "memory_leak",
    "checkpoint", "checkpoint_wait",
    "sync_contention", "sync_hold", "sync_watchdog", "sync_inversion",
    "profiling_capture", "profiling_step",
    "serving_request", "serving_shed", "serving_timeout",
    "serving_batch", "serving_latency", "serving_warmup",
    "serving_model", "serving_compile_cache", "serving_evict",
    "serving_swap", "train_publish", "checkpoint_quarantine",
    "checkpoint_retry", "checkpoint_write_failed",
    "preemption_reentry", "chaos_inject", "chaos_survive",
    "serving_watcher_suspended", "env_health",
    "goodput_window", "goodput_regression", "goodput_env_degraded",
    "dist_rank_failure", "checkpoint_commit_aborted",
    "supervisor_restart", "supervisor_exhausted",
    "serving_error", "fleet_scrape", "fleet_replica_down",
    "fleet_round", "fleet_alert", "fleet_alerts_firing",
    "decode_request", "decode_shed", "decode_prefill", "decode_step",
    "decode_ttft", "decode_inter_token", "decode_finish",
    "kvcache_alloc", "kvcache_free", "kvcache_alloc_failure",
]


def _registry():
    # late import: telemetry/__init__ rebinds the module-global registry
    # on reset; resolving through the package keeps hooks working
    from . import _registry
    return _registry


def op_dispatch(opname):
    reg = _registry()
    reg.counter("dispatch.op_calls").inc()
    reg.counter("dispatch.op." + opname).inc()


def host_sync(kind, seconds=None):
    reg = _registry()
    reg.counter("dispatch.host_sync").inc()
    reg.counter("dispatch.host_sync." + kind).inc()
    if seconds is not None:
        # the goodput ledger's host_sync category: wall the host spent
        # blocked on device results (asnumpy / wait_to_read / waitall)
        reg.timer("dispatch.host_sync_time").observe(seconds, sync=kind)


def compile_event(site, seconds=None, retrace=False, **payload):
    """One XLA trace/compile happened at ``site`` (``hybrid_cache``,
    ``executor.train``, ``executor.eval``, ``eager_jit``).  ``retrace``
    marks a compile that joined a non-empty cache -- the runtime analog
    of the static retrace auditor's findings; ``payload`` carries the
    cache-key diff."""
    reg = _registry()
    reg.counter("compile.count").inc()
    if retrace:
        reg.counter("compile.retraces").inc()
    if seconds is not None:
        reg.timer("compile.build_time").observe(seconds, site=site)
    reg.event("compile").emit(site=site, retrace=bool(retrace),
                              seconds=seconds, **payload)


def trainer_step(seconds, batch_size):
    reg = _registry()
    reg.timer("trainer.step_time").observe(seconds)
    reg.counter("trainer.steps").inc()
    if batch_size:
        reg.counter("trainer.samples").inc(int(batch_size))
        if seconds > 0:
            reg.gauge("trainer.samples_per_sec").set(batch_size / seconds)


def samples_per_sec(value):
    """Throughput reported by an outer logger (callback.Speedometer):
    same gauge the Trainer feeds, so Module-API and Gluon training
    report through one channel."""
    _registry().gauge("trainer.samples_per_sec").set(value)


def kv_op(verb, nbytes, seconds=None):
    reg = _registry()
    reg.counter("kvstore." + verb).inc()
    if nbytes:
        reg.counter("kvstore.bytes").inc(int(nbytes))
    if seconds is not None:
        reg.timer("kvstore.time").observe(seconds, verb=verb)


def dist_collective(kind, nbytes, ntensors=1):
    """One host-side cross-process collective (distributed.py).  The
    hot training path moves ZERO bytes through here (gradients reduce
    in-graph, docs/distributed.md); what remains is init-time broadcast
    and metric/overflow reduction, and the bucketed wrappers coalesce
    N tensors into one call -- ``dist.collectives`` vs
    ``dist.tensors_coalesced`` is the call-count-drop proof."""
    reg = _registry()
    reg.counter("dist.collectives").inc()
    reg.counter("dist." + kind).inc()
    if nbytes:
        reg.counter("dist.bytes").inc(int(nbytes))
    if ntensors:
        reg.counter("dist.tensors_coalesced").inc(int(ntensors))


def dataloader_wait(seconds):
    reg = _registry()
    reg.counter("data.batches").inc()
    reg.timer("data.wait_time").observe(seconds)


def feed_produce(seconds, nbytes):
    reg = _registry()
    reg.counter("feed.batches").inc()
    if nbytes:
        reg.counter("feed.bytes_staged").inc(int(nbytes))
    reg.timer("feed.producer_busy").observe(seconds)


def feed_wait(seconds):
    _registry().timer("feed.consumer_wait").observe(seconds)


def feed_overlap(frac):
    _registry().gauge("feed.overlap_frac").set(frac)


def amp_overflow(scale_before, scale_after):
    reg = _registry()
    reg.counter("amp.overflows").inc()
    reg.gauge("amp.loss_scale").set(scale_after)
    reg.event("amp.overflow").emit(scale_before=scale_before,
                                   scale_after=scale_after)


def amp_rescale(scale_before, scale_after):
    reg = _registry()
    reg.gauge("amp.loss_scale").set(scale_after)
    reg.event("amp.rescale").emit(scale_before=scale_before,
                                  scale_after=scale_after)


def numerics_check(seconds=None):
    """One non-finite sentinel check ran (analysis.numerics; armed by
    MXNET_TPU_NUMERICS_CHECK=1).  ``seconds`` is the host wall spent on
    the one boolean device_get."""
    reg = _registry()
    reg.counter("numerics.checks").inc()
    if seconds is not None:
        reg.timer("numerics.check_time").observe(seconds)


def numerics_nonfinite(param, step, kind):
    """The sentinel attributed a non-finite step: ``param`` is the
    first offending parameter (or ``loss``), ``kind`` nan/inf."""
    reg = _registry()
    reg.counter("numerics.nonfinite_steps").inc()
    reg.event("numerics.nonfinite").emit(param=param, step=step,
                                         kind=kind)


def memory_census(live_bytes, live_arrays):
    """One live-buffer census ran (analysis.memory; armed by
    MXNET_TPU_MEMORY_WATCH=1): publish the live totals as gauges."""
    reg = _registry()
    reg.counter("memory.censuses").inc()
    reg.gauge("memory.live_bytes").set(live_bytes)
    reg.gauge("memory.live_arrays").set(live_arrays)


def memory_leak(bucket, growth_bytes, live_bytes, window):
    """The leak sentinel flagged monotonic live-bytes growth; payload
    names the top-growing shape/dtype bucket."""
    reg = _registry()
    reg.counter("memory.leaks").inc()
    reg.event("memory.leak").emit(bucket=bucket,
                                  growth_bytes=growth_bytes,
                                  live_bytes=live_bytes, window=window)


def checkpoint(action, nbytes=None, seconds=None, **payload):
    reg = _registry()
    reg.counter("checkpoint.%ss" % action).inc()
    if nbytes:
        reg.counter("checkpoint.bytes_read" if action == "restore"
                    else "checkpoint.bytes_written").inc(int(nbytes))
    if seconds is not None:
        reg.timer("checkpoint.%s_time" % action).observe(seconds)
    reg.event("checkpoint").emit(action=action, nbytes=nbytes,
                                 seconds=seconds, **payload)


def checkpoint_wait(seconds, step=None):
    reg = _registry()
    reg.timer("checkpoint.async_wait").observe(
        seconds, **({} if step is None else {"step": step}))


def sync_contention(lock_name, seconds):
    _registry().timer("sync.contention_wait").observe(seconds,
                                                      lock=lock_name)


def sync_hold(lock_name, seconds):
    _registry().timer("sync.hold_time").observe(seconds, lock=lock_name)


def sync_watchdog(lock_name):
    reg = _registry()
    reg.counter("sync.watchdog_fires").inc()
    reg.event("sync.watchdog").emit(lock=lock_name)


def sync_inversion(outer, inner):
    reg = _registry()
    reg.counter("sync.inversions").inc()
    reg.event("sync.inversion").emit(outer=outer, inner=inner)


def profiling_capture(label, seconds, flops=None):
    """One CostReport was materialized by the mx.profiling store."""
    reg = _registry()
    reg.counter("profiling.reports").inc()
    reg.timer("profiling.capture_time").observe(seconds, label=label)
    reg.event("profiling.capture").emit(label=label, seconds=seconds,
                                        flops=flops)


def profiling_step(label, seconds):
    """One step wall time recorded for the roofline clock."""
    _registry().timer("profiling.step_time").observe(seconds,
                                                     label=label)


def serving_request(model, queue_depth):
    reg = _registry()
    reg.counter("serving.requests").inc()
    reg.gauge("serving.queue_depth").set(queue_depth)


def serving_shed(model):
    _registry().counter("serving.shed").inc()


def serving_timeout(model):
    _registry().counter("serving.timeouts").inc()


def serving_error(model):
    """A compiled dispatch raised: the batch's requests were failed but
    the worker survived -- the error_ratio numerator the fleet plane
    scrapes."""
    _registry().counter("serving.errors").inc()


def serving_batch(model, occupancy, bucket, seconds):
    """One compiled batch dispatched: ``occupancy`` real requests
    padded to ``bucket``."""
    reg = _registry()
    reg.counter("serving.batches").inc()
    reg.counter("serving.responses").inc(int(occupancy))
    reg.gauge("serving.batch_occupancy").set(occupancy)
    reg.timer("serving.dispatch_time").observe(seconds, model=model,
                                               bucket=bucket,
                                               occupancy=occupancy)


def serving_latency(seconds):
    _registry().timer("serving.latency").observe(seconds)


def serving_warmup(model, seconds, n_buckets):
    _registry().timer("serving.warmup_time").observe(
        seconds, model=model, buckets=n_buckets)


def serving_model(model, source, n_buckets):
    reg = _registry()
    reg.counter("serving.models").inc()
    reg.event("serving.register").emit(model=model, source=source,
                                       buckets=n_buckets)


def serving_compile_cache(hit):
    _registry().counter("serving.compile_cache_hits" if hit
                        else "serving.compile_cache_misses").inc()


def serving_evict():
    _registry().counter("serving.compile_evictions").inc()


def serving_swap(model, step, seconds, ok, from_step=None, attempt=1,
                 error=None):
    """One hot-swap attempt by a RegistryWatcher finished."""
    reg = _registry()
    if ok:
        reg.counter("serving.swaps").inc()
        reg.timer("serving.swap_time").observe(seconds, model=model,
                                               step=step)
        reg.gauge("serving.served_step").set(step)
    else:
        reg.counter("serving.swap_failures").inc()
    reg.event("serving.swap").emit(model=model, step=step, ok=bool(ok),
                                   from_step=from_step, attempt=attempt,
                                   seconds=seconds, error=error)


def decode_request(model, queue_depth):
    """One generation request admitted to a decode engine."""
    reg = _registry()
    reg.counter("decode.requests").inc()
    reg.gauge("decode.queue_depth").set(queue_depth)


def decode_shed(model, reason):
    """Admission backpressure: a generation request was shed at submit
    (``reason``: ``queue`` = pending queue full, ``kvcache`` = the KV
    cache cannot cover the request's whole token budget)."""
    reg = _registry()
    reg.counter("decode.shed").inc()
    reg.counter("decode.shed." + reason).inc()


def decode_prefill(model, bucket, prompt_len, seconds):
    """One prompt prefilled into cache blocks (the first token's
    compiled call, bucketed by padded prompt length)."""
    reg = _registry()
    reg.counter("decode.prefills").inc()
    reg.timer("decode.prefill_time").observe(seconds, model=model,
                                             bucket=bucket,
                                             prompt_len=prompt_len)


def decode_step(model, occupancy, bucket, seconds):
    """One continuous-batching decode iteration: ``occupancy`` live
    sequences padded to the ``bucket`` slot count."""
    reg = _registry()
    reg.counter("decode.steps").inc()
    reg.counter("decode.tokens").inc(int(occupancy))
    reg.gauge("decode.occupancy").set(occupancy)
    reg.timer("decode.step_time").observe(seconds, model=model,
                                          bucket=bucket,
                                          occupancy=occupancy)


def decode_ttft(seconds):
    """Submit -> first streamed token (the product-layer TTFT)."""
    _registry().timer("decode.ttft").observe(seconds)


def decode_inter_token(seconds):
    """Gap between consecutive streamed tokens of one request."""
    _registry().timer("decode.inter_token").observe(seconds)


def decode_finish(model, reason, tokens):
    """One generation finished (``reason``: eos / length / cancel /
    timeout / error / closed)."""
    reg = _registry()
    reg.counter("decode.finished").inc()
    reg.event("decode.finish").emit(model=model, reason=reason,
                                    tokens=int(tokens))


def kvcache_alloc(in_use, fragmentation):
    """A block-table allocation succeeded; gauges carry the cache's
    post-alloc occupancy and internal fragmentation (unused fraction
    of allocated blocks)."""
    reg = _registry()
    reg.counter("kvcache.allocs").inc()
    reg.gauge("kvcache.blocks_in_use").set(in_use)
    reg.gauge("kvcache.fragmentation").set(fragmentation)


def kvcache_free(in_use, fragmentation):
    """A finished/cancelled sequence returned its blocks."""
    reg = _registry()
    reg.counter("kvcache.frees").inc()
    reg.gauge("kvcache.blocks_in_use").set(in_use)
    reg.gauge("kvcache.fragmentation").set(fragmentation)


def kvcache_alloc_failure():
    """An allocation found too few free blocks (the admission-shed
    trigger; never fires mid-generation by construction)."""
    _registry().counter("kvcache.alloc_failures").inc()


def train_publish(step, seconds):
    """ContinuousTrainer published a checkpoint for the watcher."""
    reg = _registry()
    reg.counter("train_loop.publishes").inc()
    reg.gauge("train_loop.published_step").set(step)
    reg.event("train_loop.publish").emit(step=step, seconds=seconds)


def checkpoint_quarantine(step, path):
    """Discovery renamed a verification-failed step to .corrupt."""
    reg = _registry()
    reg.counter("checkpoint.quarantined").inc()
    reg.event("checkpoint.quarantine").emit(step=step, path=path)


def checkpoint_retry(attempt, error, step=None):
    """The async writer retried a failed background write."""
    reg = _registry()
    reg.counter("checkpoint.write_retries").inc()
    reg.event("checkpoint.write_retry").emit(attempt=attempt,
                                             error=error, step=step)


def checkpoint_write_failed(attempts, error, step=None):
    """An async write failed every attempt (error re-raises at the
    next save/wait; this event is the operator-visible surface)."""
    reg = _registry()
    reg.counter("checkpoint.write_failures").inc()
    reg.event("checkpoint.write_failed").emit(attempts=attempts,
                                              error=error, step=step)


def preemption_reentry():
    _registry().counter("preemption.reentrant_signals").inc()


def chaos_inject(point, action):
    """An armed fail point fired."""
    reg = _registry()
    reg.counter("chaos.injected").inc()
    reg.counter("chaos.injected." + point).inc()
    reg.event("chaos.inject").emit(point=point, action=action)


def chaos_survive(point, how):
    """A recovery path tolerated a fault (injected or real)."""
    reg = _registry()
    reg.counter("chaos.survived").inc()
    reg.counter("chaos.survived." + point).inc()
    reg.event("chaos.survive").emit(point=point, how=how)


def dist_rank_failure(kind, tag, ranks, elapsed_s=None):
    """A host collective or barrier gave up on peer rank(s) -- the
    typed RankFailure/BarrierTimeout surface (distributed.py), never a
    raw jaxlib deadline.  ``kind``: barrier/collective/abort."""
    reg = _registry()
    reg.counter("dist.rank_failures").inc()
    reg.event("dist.rank_failure").emit(kind=kind, tag=tag,
                                        ranks=list(ranks),
                                        elapsed_s=elapsed_s)


def checkpoint_commit_aborted(step, reason, rank=None):
    """A sharded save aborted cleanly instead of committing -- staged
    tmp swept, manifest never renamed in (the rank-death-safe commit
    contract, checkpoint/sharded.py)."""
    reg = _registry()
    reg.counter("checkpoint.commit_aborted").inc()
    reg.event("checkpoint.commit_abort").emit(step=step, reason=reason,
                                              rank=rank)


def supervisor_restart(generation, rank, exit_code, restarts):
    """The elastic restart supervisor relaunched the world after a
    rank death (mxnet_tpu/supervisor.py)."""
    reg = _registry()
    reg.counter("supervisor.restarts").inc()
    reg.gauge("supervisor.generation").set(generation)
    reg.event("supervisor.restart").emit(generation=generation,
                                         rank=rank,
                                         exit_code=exit_code,
                                         restarts=restarts)


def supervisor_exhausted(generation, budget):
    """The supervisor's restart budget ran out -- it stops relaunching
    and /healthz reads NOT_READY off the same state; alert here."""
    reg = _registry()
    reg.counter("supervisor.budget_exhausted").inc()
    reg.event("supervisor.exhausted").emit(generation=generation,
                                           budget=budget)


def serving_watcher_suspended(model, step, budget):
    """A RegistryWatcher exhausted its swap failure budget and went
    terminal -- it will never retry on its own, so this is the event an
    operator alert must hang off (and /healthz reads NOT_READY)."""
    reg = _registry()
    reg.counter("serving.watcher_suspensions").inc()
    reg.event("serving.watcher_suspended").emit(model=model, step=step,
                                                budget=budget)


def goodput_window(report):
    """One StepLedger window closed (obs.goodput): publish the
    attribution as gauges (shares, MFU -- the live/Prometheus view),
    timers (per-category seconds -- the per-rank offline view: timer
    sums survive into summarize, so rank files carry per-category
    totals), and one compact ``goodput.window`` event."""
    reg = _registry()
    reg.counter("goodput.windows").inc()
    if report["steps"]:
        reg.counter("goodput.steps").inc(int(report["steps"]))
    for cat, c in report["categories"].items():
        reg.timer("goodput." + cat + "_s").observe(c["seconds"])
        reg.gauge("goodput." + cat + "_share").set(c["share"])
    if report.get("mfu") is not None:
        reg.gauge("goodput.mfu").set(report["mfu"])
    reg.gauge("goodput.reconciliation_error").set(
        report["reconciliation"]["error"])
    reg.event("goodput.window").emit(
        index=report["index"], reason=report["reason"],
        steps=report["steps"], wall_s=round(report["wall_s"], 6),
        mfu=report.get("mfu"),
        shares={cat: round(c["share"], 4)
                for cat, c in report["categories"].items()},
        verdict=report["verdict"]["detail"],
        bound=report["verdict"]["bound"],
        reconciled=report["reconciliation"]["ok"],
        env_degraded=report["env_degraded"])


def goodput_regression(category, per_step_s, baseline_per_step_s,
                       ratio, window):
    """The sentinel flagged one category as regressed vs its EWMA+MAD
    baseline -- the event NAMES the category that moved."""
    reg = _registry()
    reg.counter("goodput.regressions").inc()
    reg.event("goodput.regression").emit(
        category=category, per_step_s=per_step_s,
        baseline_per_step_s=baseline_per_step_s, ratio=ratio,
        window=window)


def goodput_env_degraded(window, dispatch_roundtrip_us):
    """The sentinel's env guard tripped: the window ran on a degraded
    environment (tunnel), so it is reported HERE and not as a
    regression -- the r05 lesson, and the event the bench's per-line
    ``degraded_env`` flag must agree with (test_bench_contract)."""
    reg = _registry()
    reg.counter("goodput.env_degraded_windows").inc()
    reg.event("goodput.env_degraded").emit(
        window=window, dispatch_roundtrip_us=dispatch_roundtrip_us)


def fleet_scrape(ok):
    """One replica scrape attempt by a FleetMonitor finished."""
    reg = _registry()
    reg.counter("fleet.scrapes").inc()
    if not ok:
        reg.counter("fleet.scrape_failures").inc()


def fleet_replica_down(rank, generation, error):
    """A replica flipped to presumed-down (dead pid, stale past TTL,
    or scrape failures outliving the lease) -- the event NAMES the
    rank and generation so the page is actionable."""
    reg = _registry()
    reg.counter("fleet.replica_downs").inc()
    reg.event("fleet.replica_down").emit(rank=rank,
                                         generation=generation,
                                         error=error)


def fleet_round(agg):
    """One fleet aggregation round: publish the pooled view as gauges
    (obs.fleet.FleetMonitor)."""
    reg = _registry()
    reg.gauge("fleet.replicas").set(agg["replicas"])
    reg.gauge("fleet.replicas_down").set(agg["down"])
    if agg.get("qps") is not None:
        reg.gauge("fleet.qps").set(agg["qps"])
    reg.gauge("fleet.queue_depth").set(agg["queue_depth"])
    if agg.get("shed_ratio") is not None:
        reg.gauge("fleet.shed_ratio").set(agg["shed_ratio"])
    if agg.get("error_ratio") is not None:
        reg.gauge("fleet.error_ratio").set(agg["error_ratio"])
    lat = agg.get("latency_ms") or {}
    for q in ("p50", "p95", "p99"):
        if lat.get(q) is not None:
            reg.gauge("fleet.latency_%s_ms" % q).set(lat[q])
    skew = (agg.get("served_step") or {}).get("skew")
    if skew is not None:
        reg.gauge("fleet.served_step_skew").set(skew)


def fleet_alert(rule, state, reason, value):
    """One alert state transition (obs.alerts.AlertEngine)."""
    _registry().event("fleet.alert").emit(rule=rule, state=state,
                                          reason=reason, value=value)


def fleet_alerts_firing(n):
    """Currently-firing alert count (the pageable surface)."""
    _registry().gauge("fleet.alerts_firing").set(n)


def env_health(dispatch_roundtrip_us, h2d_mb_per_s=None):
    """The bench environment-health probe's numbers, recorded so the
    basis of a `degraded_env` verdict appears in summarize and in the
    flight-recorder dump instead of dying with the bench stdout."""
    reg = _registry()
    reg.gauge("env.dispatch_roundtrip_us").set(dispatch_roundtrip_us)
    if h2d_mb_per_s is not None:
        reg.gauge("env.h2d_mb_per_s").set(h2d_mb_per_s)
    reg.event("env.health").emit(
        dispatch_roundtrip_us=dispatch_roundtrip_us,
        h2d_mb_per_s=h2d_mb_per_s)


# ----------------------------------------------------------------------
# the instrument catalogue -- data the docs are generated from
# ----------------------------------------------------------------------

class InstrumentInfo:
    """One catalogued instrument: (name, kind, subsystem, since-PR,
    meaning).  ``name`` may carry a ``<placeholder>`` segment for
    per-key instrument families (``dispatch.op.<op>``)."""

    __slots__ = ("name", "kind", "subsystem", "since", "doc")

    def __init__(self, name, kind, subsystem, since, doc):
        self.name = name
        self.kind = kind
        self.subsystem = subsystem
        self.since = since
        self.doc = doc


def _ii(name, kind, subsystem, since, doc):
    return InstrumentInfo(name, kind, subsystem, since, doc)


INSTRUMENTS = [
    _ii("dispatch.op_calls", "counter", "ndarray", 2,
        "imperative op invocations (total)"),
    _ii("dispatch.op.<op>", "counter", "ndarray", 2,
        "per-op invocation count"),
    _ii("dispatch.host_sync", "counter", "ndarray", 2,
        "host sync points (asnumpy/wait/waitall)"),
    _ii("dispatch.host_sync.<kind>", "counter", "ndarray", 2,
        "per-kind sync count"),
    _ii("compile", "event", "compile", 2,
        "one per XLA trace/compile; payload says where and why "
        "(cache-key diff on retrace)"),
    _ii("compile.count", "counter", "compile", 2, "total compiles"),
    _ii("compile.retraces", "counter", "compile", 2,
        "compiles that REPLACED warm cache state"),
    _ii("compile.build_time", "timer", "compile", 2,
        "wall time spent tracing/compiling"),
    _ii("trainer.step_time", "timer", "trainer", 2,
        "Trainer.step wall time"),
    _ii("trainer.steps", "counter", "trainer", 2,
        "optimizer steps taken"),
    _ii("trainer.samples", "counter", "trainer", 2,
        "samples pushed through step()"),
    _ii("trainer.samples_per_sec", "gauge", "trainer", 2,
        "throughput (Trainer.step + Speedometer)"),
    _ii("kvstore.push", "counter", "kvstore", 2,
        "kvstore push calls"),
    _ii("kvstore.pull", "counter", "kvstore", 2,
        "kvstore pull calls"),
    _ii("kvstore.pushpull", "counter", "kvstore", 2,
        "kvstore fused pushpull calls"),
    _ii("kvstore.bytes", "counter", "kvstore", 2,
        "gradient bytes moved through kvstore (ZERO on the SPMD hot "
        "path -- gradients reduce in-graph)"),
    _ii("kvstore.time", "timer", "kvstore", 2,
        "wall time in pushpull (dispatch side)"),
    _ii("dist.collectives", "counter", "distributed", 9,
        "host-side cross-process collectives issued"),
    _ii("dist.<kind>", "counter", "distributed", 9,
        "per-kind collective count (allreduce/broadcast/...)"),
    _ii("dist.bytes", "counter", "distributed", 9,
        "bytes moved by host collectives"),
    _ii("dist.tensors_coalesced", "counter", "distributed", 9,
        "tensors folded into bucketed collectives (vs dist.collectives "
        "= the coalescing win)"),
    _ii("data.batches", "counter", "dataio", 2,
        "batches produced by DataLoader"),
    _ii("data.wait_time", "timer", "dataio", 2,
        "consumer wait per batch (input starvation when this rivals "
        "step_time)"),
    _ii("feed.batches", "counter", "dataio", 4,
        "batches staged by dataio.DeviceFeed"),
    _ii("feed.bytes_staged", "counter", "dataio", 4,
        "bytes shipped host->device by the feed"),
    _ii("feed.producer_busy", "timer", "dataio", 4,
        "per-batch producer time (host batch + async device_put "
        "issue)"),
    _ii("feed.consumer_wait", "timer", "dataio", 4,
        "per-batch consumer wait on the staging queue"),
    _ii("feed.overlap_frac", "gauge", "dataio", 4,
        "share of producer time hidden behind compute: 1 - wait/busy"),
    _ii("amp.overflow", "event", "amp", 2,
        "fp16 grad overflow (scale halved)"),
    _ii("amp.overflows", "counter", "amp", 2, "total overflow steps"),
    _ii("amp.rescale", "event", "amp", 2,
        "loss-scale growth after a clean window"),
    _ii("amp.loss_scale", "gauge", "amp", 2, "current loss scale"),
    _ii("numerics.checks", "counter", "numerics", 16,
        "non-finite sentinel checks run (MXNET_TPU_NUMERICS_CHECK=1)"),
    _ii("numerics.check_time", "timer", "numerics", 16,
        "host wall per sentinel check (the one boolean device_get)"),
    _ii("numerics.nonfinite_steps", "counter", "numerics", 16,
        "steps the sentinel attributed a NaN/Inf gradient on"),
    _ii("numerics.nonfinite", "event", "numerics", 16,
        "one per attributed non-finite step; payload names the first "
        "offending parameter, the step, and nan-vs-inf"),
    _ii("memory.censuses", "counter", "memory", 19,
        "live-buffer censuses run (MXNET_TPU_MEMORY_WATCH=1)"),
    _ii("memory.live_bytes", "gauge", "memory", 19,
        "total bytes of jax.live_arrays() at the last census"),
    _ii("memory.live_arrays", "gauge", "memory", 19,
        "live device-array count at the last census"),
    _ii("memory.leaks", "counter", "memory", 19,
        "windows the leak sentinel flagged monotonic live-bytes "
        "growth on"),
    _ii("memory.leak", "event", "memory", 19,
        "one per flagged leak window; payload names the top-growing "
        "shape/dtype bucket, the growth bytes, and the window index"),
    _ii("checkpoint", "event", "checkpoint", 2,
        "checkpoint save/restore; payload carries step/bytes/duration"),
    _ii("checkpoint.saves", "counter", "checkpoint", 3,
        "saves (incl. provisional)"),
    _ii("checkpoint.restores", "counter", "checkpoint", 3,
        "restores (preemption resume + manager)"),
    _ii("checkpoint.bytes_written", "counter", "checkpoint", 3,
        "bytes committed by saves"),
    _ii("checkpoint.bytes_read", "counter", "checkpoint", 3,
        "bytes loaded by restores"),
    _ii("checkpoint.save_time", "timer", "checkpoint", 3,
        "wall time serializing+committing a save"),
    _ii("checkpoint.restore_time", "timer", "checkpoint", 3,
        "wall time verifying+loading a restore"),
    _ii("checkpoint.async_wait", "timer", "checkpoint", 3,
        "time a save spent draining the previous in-flight async "
        "write"),
    _ii("checkpoint.quarantined", "counter", "checkpoint", 12,
        "verification-failed steps renamed step_<N>.corrupt during "
        "discovery"),
    _ii("checkpoint.write_retries", "counter", "checkpoint", 12,
        "async-writer attempts retried after a transient failure"),
    _ii("checkpoint.write_retry", "event", "checkpoint", 12,
        "one async-writer retry; payload carries attempt + error"),
    _ii("checkpoint.write_failures", "counter", "checkpoint", 12,
        "async writes that failed EVERY attempt (also re-raises at "
        "next save/wait; flips /healthz NOT_READY)"),
    _ii("checkpoint.write_failed", "event", "checkpoint", 12,
        "terminal async write failure; payload carries attempts + "
        "error"),
    _ii("checkpoint.quarantine", "event", "checkpoint", 12,
        "one quarantine rename; payload carries step + path"),
    _ii("sync.contention_wait", "timer", "sync", 5,
        "time blocked acquiring a contended lock (TSAN only; labeled "
        "by lock role)"),
    _ii("sync.hold_time", "timer", "sync", 5,
        "lock hold duration (TSAN only)"),
    _ii("sync.watchdog_fires", "counter", "sync", 5,
        "deadlock-watchdog expiries (TSAN only)"),
    _ii("sync.watchdog", "event", "sync", 5,
        "one watchdog expiry; payload names the lock"),
    _ii("sync.inversions", "counter", "sync", 5,
        "lock-order inversions observed (report-only mode)"),
    _ii("sync.inversion", "event", "sync", 5,
        "one inversion; payload carries outer/inner roles"),
    _ii("profiling.reports", "counter", "profiling", 6,
        "CostReports materialized by the mx.profiling store"),
    _ii("profiling.capture_time", "timer", "profiling", 6,
        "wall time lowering/parsing one report"),
    _ii("profiling.capture", "event", "profiling", 6,
        "one per report; payload carries label + FLOPs"),
    _ii("profiling.step_time", "timer", "profiling", 6,
        "per-dispatch step wall recorded by TrainStep (feeds the "
        "roofline)"),
    _ii("serving.requests", "counter", "serving", 8,
        "requests accepted by serving submit()"),
    _ii("serving.responses", "counter", "serving", 8,
        "responses scattered from dispatched batches"),
    _ii("serving.batches", "counter", "serving", 8,
        "compiled batch dispatches (mean occupancy = responses / "
        "batches)"),
    _ii("serving.batch_occupancy", "gauge", "serving", 8,
        "requests in the last dispatched batch (>1 = dynamic batching "
        "works)"),
    _ii("serving.queue_depth", "gauge", "serving", 8,
        "request-queue depth at last submit"),
    _ii("serving.shed", "counter", "serving", 8,
        "submits rejected by a full queue (ServingQueueFull)"),
    _ii("serving.timeouts", "counter", "serving", 8,
        "requests expired while queued (RequestTimeout)"),
    _ii("serving.latency", "timer", "serving", 8,
        "per-request round trip submit -> response (the SLO metric)"),
    _ii("serving.dispatch_time", "timer", "serving", 8,
        "compiled-call + device_get wall per batch (reconciles with "
        "the serving.dispatch + serving.device_get trace spans)"),
    _ii("serving.warmup_time", "timer", "serving", 8,
        "per-servable registration warm-up"),
    _ii("serving.models", "counter", "serving", 8,
        "servables registered"),
    _ii("serving.register", "event", "serving", 8,
        "one servable registration; payload carries source + buckets"),
    _ii("serving.compile_cache_hits", "counter", "serving", 8,
        "bucket executables served from the persistent compile cache"),
    _ii("serving.compile_cache_misses", "counter", "serving", 8,
        "bucket executables compiled fresh"),
    _ii("serving.compile_evictions", "counter", "serving", 8,
        "Predictor per-shape jit programs evicted by the LRU bound"),
    _ii("serving.swaps", "counter", "serving", 12,
        "successful hot-swaps to a newer verified step"),
    _ii("serving.swap_failures", "counter", "serving", 12,
        "swap attempts that aborted (previous servable kept serving)"),
    _ii("serving.swap_time", "timer", "serving", 12,
        "wall per successful swap (restore + warm + install + drain)"),
    _ii("serving.swap", "event", "serving", 12,
        "one swap attempt; payload carries step/ok/attempt/error "
        "(the /statusz swap history)"),
    _ii("serving.served_step", "gauge", "serving", 12,
        "checkpoint step the live servable was loaded from"),
    _ii("serving.watcher_suspensions", "counter", "serving", 13,
        "watchers that exhausted the swap failure budget and went "
        "terminal"),
    _ii("serving.watcher_suspended", "event", "serving", 13,
        "the terminal suspension; payload names model/step/budget -- "
        "alert on this, /healthz reads NOT_READY off the same state"),
    _ii("train_loop.publishes", "counter", "serving", 12,
        "checkpoints published by ContinuousTrainer"),
    _ii("train_loop.published_step", "gauge", "serving", 12,
        "newest step the trainer published"),
    _ii("train_loop.publish", "event", "serving", 12,
        "one publish; payload carries step + seconds"),
    _ii("preemption.reentrant_signals", "counter", "preemption", 12,
        "re-entrant SIGTERM deliveries suppressed mid-commit"),
    _ii("chaos.injected", "counter", "chaos", 12,
        "faults injected by armed fail points"),
    _ii("chaos.injected.<point>", "counter", "chaos", 12,
        "per-point injected count"),
    _ii("chaos.inject", "event", "chaos", 12,
        "one injection; payload carries point + action"),
    _ii("chaos.survived", "counter", "chaos", 12,
        "faults tolerated by a recovery path (injected or real)"),
    _ii("chaos.survived.<point>", "counter", "chaos", 12,
        "per-point survived count"),
    _ii("chaos.survive", "event", "chaos", 12,
        "one tolerated fault; payload carries point + how"),
    _ii("dispatch.host_sync_time", "timer", "ndarray", 14,
        "wall the host spent blocked on device results "
        "(asnumpy/wait_to_read/waitall) -- the goodput ledger's "
        "host_sync category"),
    _ii("goodput.windows", "counter", "goodput", 14,
        "StepLedger windows closed"),
    _ii("goodput.steps", "counter", "goodput", 14,
        "training steps attributed by the ledger"),
    _ii("goodput.<category>_s", "timer", "goodput", 14,
        "per-window seconds attributed to the category "
        "(device_compute/input_wait/host_sync/checkpoint_stall/"
        "recompile/other); timer sums give per-rank category totals "
        "offline"),
    _ii("goodput.<category>_share", "gauge", "goodput", 14,
        "last window's share of wall per category"),
    _ii("goodput.mfu", "gauge", "goodput", 14,
        "rolling MFU: window flops (executable cost report) / wall / "
        "device peak"),
    _ii("goodput.reconciliation_error", "gauge", "goodput", 14,
        "last window's attribution overshoot vs wall (0 unless "
        "categories double-count; CI gates <= tol)"),
    _ii("goodput.window", "event", "goodput", 14,
        "one closed window; payload carries steps/wall/shares/mfu + "
        "the bottleneck verdict sentence"),
    _ii("goodput.regressions", "counter", "goodput", 14,
        "windows where the sentinel flagged a category vs its "
        "EWMA+MAD baseline"),
    _ii("goodput.regression", "event", "goodput", 14,
        "one flagged regression; payload NAMES the category that "
        "moved (per-step seconds vs baseline, ratio)"),
    _ii("goodput.env_degraded_windows", "counter", "goodput", 14,
        "windows the sentinel attributed to a degraded environment "
        "(env guard) instead of a regression"),
    _ii("goodput.env_degraded", "event", "goodput", 14,
        "one env-guarded window; payload carries the dispatch RTT -- "
        "must agree with the bench line's degraded_env flag"),
    _ii("dist.rank_failures", "counter", "distributed", 15,
        "host collectives/barriers that gave up on peer rank(s) -- "
        "surfaced as typed RankFailure/BarrierTimeout naming the "
        "rank, never a raw jaxlib deadline"),
    _ii("dist.rank_failure", "event", "distributed", 15,
        "one attributed failure; payload carries kind/tag/ranks/"
        "elapsed"),
    _ii("checkpoint.commit_aborted", "counter", "checkpoint", 15,
        "sharded saves that aborted cleanly on a rank failure "
        "(staging swept, manifest never committed -- the rank-death-"
        "safe commit contract)"),
    _ii("checkpoint.commit_abort", "event", "checkpoint", 15,
        "one clean abort; payload carries step/reason/rank"),
    _ii("supervisor.restarts", "counter", "supervisor", 15,
        "elastic world relaunches after a rank death "
        "(tools/launch.py --supervise)"),
    _ii("supervisor.generation", "gauge", "supervisor", 15,
        "current supervisor generation id (namespaces the "
        "coordination-KV keys; bumped on every relaunch)"),
    _ii("supervisor.restart", "event", "supervisor", 15,
        "one relaunch; payload carries generation/dead rank/exit "
        "code/restart count"),
    _ii("supervisor.budget_exhausted", "counter", "supervisor", 15,
        "supervisors whose restart budget ran out (terminal; "
        "/healthz reads NOT_READY)"),
    _ii("supervisor.exhausted", "event", "supervisor", 15,
        "the terminal budget exhaustion; payload carries generation + "
        "budget -- alert on this"),
    _ii("serving.errors", "counter", "serving", 17,
        "compiled dispatches that raised (requests failed, worker "
        "survived) -- the fleet error_ratio numerator"),
    _ii("fleet.scrapes", "counter", "fleet", 17,
        "replica scrape attempts by a FleetMonitor"),
    _ii("fleet.scrape_failures", "counter", "fleet", 17,
        "scrape attempts that failed every retry"),
    _ii("fleet.replicas", "gauge", "fleet", 17,
        "replicas currently tracked by the monitor"),
    _ii("fleet.replicas_down", "gauge", "fleet", 17,
        "replicas presumed down (dead pid / stale past TTL)"),
    _ii("fleet.replica_downs", "counter", "fleet", 17,
        "down transitions observed"),
    _ii("fleet.replica_down", "event", "fleet", 17,
        "one down transition; payload NAMES rank + generation + the "
        "last scrape error"),
    _ii("fleet.qps", "gauge", "fleet", 17,
        "pooled accepted-request rate over the rolling window"),
    _ii("fleet.queue_depth", "gauge", "fleet", 17,
        "summed request-queue depth across up replicas"),
    _ii("fleet.shed_ratio", "gauge", "fleet", 17,
        "shed / (accepted + shed) over the rolling window"),
    _ii("fleet.error_ratio", "gauge", "fleet", 17,
        "(errors + timeouts) / responses over the rolling window"),
    _ii("fleet.latency_<q>_ms", "gauge", "fleet", 17,
        "fleet latency percentile (p50/p95/p99) from MERGED Timer "
        "histogram buckets across replicas -- never an average of "
        "per-replica percentiles"),
    _ii("fleet.served_step_skew", "gauge", "fleet", 17,
        "max - min served checkpoint step across up replicas"),
    _ii("fleet.alerts_firing", "gauge", "fleet", 17,
        "currently-firing SLO alerts (page while > 0; mxtelemetry "
        "fleet exits 1)"),
    _ii("fleet.alert", "event", "fleet", 17,
        "one alert state transition (pending/firing/resolved/"
        "cancelled); payload carries rule + reason naming the "
        "replica"),
    _ii("decode.requests", "counter", "serving", 18,
        "generation requests admitted to a decode engine"),
    _ii("decode.queue_depth", "gauge", "serving", 18,
        "generation requests waiting for a decode slot"),
    _ii("decode.shed", "counter", "serving", 18,
        "generation requests shed at admission (queue full or KV "
        "budget unavailable; never mid-generation)"),
    _ii("decode.shed.<reason>", "counter", "serving", 18,
        "per-reason shed count (queue / kvcache)"),
    _ii("decode.prefills", "counter", "serving", 18,
        "prompt prefill calls (one per admitted request)"),
    _ii("decode.prefill_time", "timer", "serving", 18,
        "prefill call wall time, tagged bucket + prompt_len"),
    _ii("decode.steps", "counter", "serving", 18,
        "continuous-batching decode iterations"),
    _ii("decode.tokens", "counter", "serving", 18,
        "tokens decoded (occupancy summed over steps)"),
    _ii("decode.occupancy", "gauge", "serving", 18,
        "live sequences in the running decode batch"),
    _ii("decode.step_time", "timer", "serving", 18,
        "decode iteration wall time, tagged bucket + occupancy"),
    _ii("decode.ttft", "timer", "serving", 18,
        "submit -> first streamed token (product-layer TTFT)"),
    _ii("decode.inter_token", "timer", "serving", 18,
        "gap between consecutive streamed tokens of one request"),
    _ii("decode.finished", "counter", "serving", 18,
        "generations finished (any reason)"),
    _ii("decode.finish", "event", "serving", 18,
        "one finished generation; payload carries reason (eos/length/"
        "cancel/timeout/error/closed) + token count"),
    _ii("kvcache.allocs", "counter", "serving", 18,
        "block-table allocations (one per admitted request)"),
    _ii("kvcache.frees", "counter", "serving", 18,
        "block tables returned (EOS/length/cancel/timeout/error)"),
    _ii("kvcache.alloc_failures", "counter", "serving", 18,
        "allocations refused for too few free blocks (admission-shed "
        "trigger)"),
    _ii("kvcache.blocks_in_use", "gauge", "serving", 18,
        "KV cache blocks currently allocated across live sequences"),
    _ii("kvcache.fragmentation", "gauge", "serving", 18,
        "unused fraction of allocated KV blocks (internal "
        "fragmentation; at worst one partial block per sequence)"),
    _ii("env.dispatch_roundtrip_us", "gauge", "bench", 13,
        "bench env-health dispatch round trip (the degraded_env "
        "basis)"),
    _ii("env.h2d_mb_per_s", "gauge", "bench", 13,
        "bench env-health host->device bandwidth"),
    _ii("env.health", "event", "bench", 13,
        "one env-health probe; payload carries both numbers"),
]

_INDEX_BEGIN = "<!-- instrument-index:begin (generated; do not edit" \
    " -- python -c 'from mxnet_tpu.telemetry import hooks; " \
    "hooks.update_observability_doc()') -->"
_INDEX_END = "<!-- instrument-index:end -->"


def instrument_index_md():
    """The generated markdown instrument index (without markers)."""
    lines = ["| Instrument | Kind | Subsystem | Since | Meaning |",
             "|---|---|---|---|---|"]
    for ii in INSTRUMENTS:
        lines.append("| `%s` | %s | %s | PR %d | %s |"
                     % (ii.name, ii.kind, ii.subsystem, ii.since,
                        ii.doc))
    return "\n".join(lines) + "\n"


def update_observability_doc(path=None):
    """Regenerate the instrument index between the markers in
    ``docs/observability.md`` (the docs/env_vars.md contract: the table
    is generated from the registry the hooks actually use, so it cannot
    drift).  Returns the new file text."""
    import os
    if path is None:
        path = os.path.join(os.path.dirname(__file__), "..", "..",
                            "docs", "observability.md")
    with open(path) as f:
        text = f.read()
    try:
        head, rest = text.split(_INDEX_BEGIN, 1)
        _old, tail = rest.split(_INDEX_END, 1)
    except ValueError:
        raise RuntimeError(
            "observability doc %s is missing the instrument-index "
            "markers" % path)
    new = (head + _INDEX_BEGIN + "\n" + instrument_index_md()
           + _INDEX_END + tail)
    with open(path, "w") as f:
        f.write(new)
    return new
