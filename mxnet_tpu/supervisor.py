"""Elastic restart supervisor for multi-process training (ISSUE 15).

``tools/launch.py --supervise`` (or :class:`Supervisor` directly)
watches the worker ranks of one *generation*.  On a TPU pod the common
failure is preemption of ONE host -- and before this module that meant
every survivor hung inside a collective, died on an unattributed
error, and the whole job was lost.  The supervised contract:

1. a rank exits nonzero (or is killed) -> the survivors notice on
   their own (typed ``BarrierTimeout``/``RankFailure`` from
   ``distributed.py``, within the barrier bound) and exit; the
   supervisor grants them ``grace_s`` to do so, then tears the process
   tree down;
2. the world's coordination-KV residue is generation-namespaced
   (``MXNET_TPU_GENERATION``): the supervisor bumps the generation and
   the NEW world's first rendezvous sweeps the dead generation's keys
   (``distributed._sweep_previous_generation``); a dead world's shared
   checkpoint staging is swept by ``CheckpointManager`` init;
3. the supervisor relaunches every rank with a fresh coordinator port
   and the bumped generation; workers resume from the newest intact
   step (``ContinuousTrainer.resume()`` -- the crash-restart contract
   the CI ``chaos_dist`` gate proves bit-identical);
4. a bounded restart budget (``MXNET_TPU_SUPERVISOR_RESTARTS``) keeps
   a persistent failure from flapping forever: exhaustion is terminal
   (``supervisor.exhausted`` event) and ``/healthz`` reads NOT_READY
   while a generation is down or the budget is spent
   (``obs.status.register_supervisor``).

Telemetry: ``supervisor.restarts`` / ``supervisor.generation`` /
``supervisor.restart`` / ``supervisor.exhausted`` (catalogued in
``telemetry/hooks.py::INSTRUMENTS``).
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

from . import chaos as _chaos
from . import obs as _obs
from . import telemetry as _telemetry
from .base import MXNetError

__all__ = ["Supervisor"]

_print_lock = threading.Lock()


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _relay(pipe, prefix):
    """Line-buffered prefixed relay (the launcher behavior): each
    worker line is ONE atomic write, so generations and ranks never
    interleave mid-line."""
    out = sys.stdout.buffer
    with pipe:
        for line in iter(pipe.readline, b""):
            if not line.endswith(b"\n"):
                line += b"\n"
            with _print_lock:
                out.write(prefix + line)
                out.flush()


class Supervisor:
    """Launch ``num_workers`` ranks of ``command`` and keep the world
    alive across rank deaths under a bounded restart budget.

    ::

        sup = Supervisor([sys.executable, "-u", "train.py"], 4)
        rc = sup.run()          # 0 = every rank of some generation
                                # finished clean

    ``None`` options defer to the env registry
    (``MXNET_TPU_SUPERVISOR_RESTARTS`` / ``_GRACE_S``); the starting
    generation comes from ``MXNET_TPU_GENERATION`` so a supervisor
    itself restarted by a higher-level manager continues the
    numbering.
    """

    def __init__(self, command, num_workers, max_restarts=None,
                 grace_s=None, env=None, endpoints_dir=None):
        from . import env as _env
        if num_workers < 1:
            raise MXNetError("Supervisor: num_workers must be >= 1")
        self.command = list(command)
        self.num_workers = int(num_workers)
        self.max_restarts = int(
            max_restarts if max_restarts is not None
            else _env.get("MXNET_TPU_SUPERVISOR_RESTARTS"))
        self.grace_s = float(grace_s if grace_s is not None
                             else _env.get("MXNET_TPU_SUPERVISOR_GRACE_S"))
        self._base_env = dict(os.environ if env is None else env)
        # the fleet discovery dir (obs.fleet, ISSUE 17): threaded into
        # every launched world so a relaunched generation's obs server
        # re-registers under the same rank automatically
        self.endpoints_dir = (
            endpoints_dir if endpoints_dir is not None
            else self._base_env.get("MXNET_TPU_OBS_ENDPOINTS_DIR", ""))
        self.generation = int(
            self._base_env.get("MXNET_TPU_GENERATION", "0") or 0)
        self.restarts = 0
        self.exhausted = False
        self._down = False
        self._procs = []
        _obs.status.register_supervisor(self)   # weak: /healthz

    # -- state ----------------------------------------------------------
    @property
    def generation_down(self):
        """True between a rank death and the next successful launch --
        and forever once the restart budget is exhausted.  /healthz
        reads NOT_READY off this."""
        return self._down or self.exhausted

    # -- lifecycle ------------------------------------------------------
    def run(self):
        """Supervise until a generation finishes clean (returns 0) or
        the restart budget is exhausted (returns the last failing
        rank's exit code)."""
        while True:
            rc, rank = self._run_generation(self.generation)
            if rc == 0:
                self._down = False
                return 0
            self._down = True
            if self.restarts >= self.max_restarts:
                self.exhausted = True
                if _telemetry._ENABLED:
                    _telemetry.hooks.supervisor_exhausted(
                        self.generation, self.max_restarts)
                self._log("restart budget (%d) exhausted; generation "
                          "%d stays down (rank %s exit %d)"
                          % (self.max_restarts, self.generation,
                             rank, rc))
                return rc
            self.restarts += 1
            self.generation += 1
            if _telemetry._ENABLED:
                _telemetry.hooks.supervisor_restart(
                    self.generation, rank, rc, self.restarts)
            # the relaunch IS the recovery path for a rank death
            _chaos.survived("supervisor.rank_exit", "relaunch")
            self._log("rank %s exited %d; relaunching generation %d "
                      "(restart %d/%d)"
                      % (rank, rc, self.generation, self.restarts,
                         self.max_restarts))

    def _log(self, msg):
        with _print_lock:
            print("supervisor: " + msg, flush=True)

    def _worker_env(self, gen, rank, coord):
        """The env one launched rank runs under (factored out of
        _spawn so the threading contract is testable without
        launching)."""
        env = dict(self._base_env)
        env.update({
            "MXNET_TPU_COORDINATOR": coord,
            "MXNET_TPU_NUM_PROCS": str(self.num_workers),
            "MXNET_TPU_PROC_ID": str(rank),
            "MXNET_TPU_GENERATION": str(gen),
        })
        if self.endpoints_dir:
            env["MXNET_TPU_OBS_ENDPOINTS_DIR"] = self.endpoints_dir
        return env

    def _spawn(self, gen, rank, coord):
        p = subprocess.Popen(self.command,
                             env=self._worker_env(gen, rank, coord),
                             start_new_session=True,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        t = threading.Thread(target=_relay,
                             args=(p.stdout, b"[g%d.%d] " % (gen, rank)),
                             daemon=True)
        t.start()
        p._relay_thread = t
        return p

    def _run_generation(self, gen):
        """One generation: fresh coordinator port, all ranks launched
        with the generation env.  Returns ``(0, None)`` when every
        rank exits clean, else ``(rc, rank)`` of the first failure
        (survivors get ``grace_s`` to exit on their own -- long enough
        for their typed BarrierTimeout -- then the tree is killed)."""
        coord = "127.0.0.1:%d" % _free_port()
        self._procs = [self._spawn(gen, rank, coord)
                       for rank in range(self.num_workers)]
        self._down = False
        procs = list(self._procs)
        first_rc, first_rank = None, None
        deadline = None
        while procs:
            for p in list(procs):
                rc = p.poll()
                if rc is None:
                    continue
                procs.remove(p)
                t = getattr(p, "_relay_thread", None)
                if t is not None:
                    t.join(timeout=10)
                if rc != 0 and first_rc is None:
                    first_rc = rc
                    first_rank = self._procs.index(p)
                    deadline = time.monotonic() + self.grace_s
            if not procs:
                break
            if deadline is not None and time.monotonic() > deadline:
                self._log("grace (%.0fs) over; killing %d straggler(s) "
                          "of generation %d"
                          % (self.grace_s, len(procs), gen))
                self._kill_tree(procs)
                break
            # fail-fast over N children needs a poll round-robin (same
            # rationale as tools/launch.py): a blocking wait on one
            # child hides a sibling's death behind it
            time.sleep(0.1)  # mxlint: disable=sleep-poll
        if first_rc is None:
            return 0, None
        self._kill_tree([p for p in self._procs if p.poll() is None])
        return first_rc, first_rank

    @staticmethod
    def _kill_tree(procs):
        """SIGTERM each straggler's process group, escalating to
        SIGKILL after a short grace (workers start in their own
        session, so wrapper grandchildren die too)."""
        import signal
        for q in procs:
            try:
                os.killpg(q.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                q.terminate()
        deadline = time.time() + 10
        for q in procs:
            try:
                q.wait(timeout=max(0.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                pass
            if q.poll() is None:
                try:
                    os.killpg(q.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    q.kill()
                q.wait()
